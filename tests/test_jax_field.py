"""Field arithmetic vs python-int oracle. Runs on CPU (conftest)."""

import secrets

import numpy as np
import pytest

from tendermint_tpu.crypto.jaxed25519 import field, pack, ref
import jax.numpy as jnp
import jax

# jit the expensive chains once — eager dispatch of ~300-op muls is slow
_invert = jax.jit(field.invert)
_pow22523 = jax.jit(field.pow22523)
_sqrt_ratio = jax.jit(field.sqrt_ratio)
_mulfreeze = jax.jit(lambda a, b: field.freeze(field.mul(a, b)))

P = ref.P


def _batch_fe(values):
    """list of ints -> (20, B) int32 device array."""
    import jax.numpy as jnp

    arr = np.stack([pack.int_to_limbs(v % P) for v in values], axis=1)
    return jnp.asarray(arr, dtype=jnp.int32)


def _to_ints(fe_arr):
    a = np.asarray(fe_arr)
    return [pack.limbs_to_int(a[:, i]) for i in range(a.shape[1])]


def _rand_vals(n):
    vals = [secrets.randbelow(P) for _ in range(n - 4)]
    return vals + [0, 1, P - 1, P - 2]


B = 12


@pytest.fixture(scope="module")
def ab():
    return _rand_vals(B), _rand_vals(B)


def test_mul(ab):
    a, b = ab
    out = _to_ints(field.mul(_batch_fe(a), _batch_fe(b)))
    for x, y, o in zip(a, b, out):
        assert o % P == (x * y) % P


def test_add_sub_neg(ab):
    a, b = ab
    fa, fb = _batch_fe(a), _batch_fe(b)
    for got, want in zip(_to_ints(field.add(fa, fb)), [(x + y) for x, y in zip(a, b)]):
        assert got % P == want % P
    for got, want in zip(_to_ints(field.sub(fa, fb)), [(x - y) for x, y in zip(a, b)]):
        assert got % P == want % P
    for got, want in zip(_to_ints(field.neg(fa)), [-x for x in a]):
        assert got % P == want % P


def test_chained_ops_respect_bounds(ab):
    """Adds/subs feeding muls — the invariant the curve formulas rely on."""
    a, b = ab
    fa, fb = _batch_fe(a), _batch_fe(b)
    s = field.add(fa, fb)
    d = field.sub(fa, fb)
    out = _to_ints(field.mul(s, d))
    for x, y, o in zip(a, b, out):
        assert o % P == ((x + y) * (x - y)) % P
    limbs = np.asarray(field.mul(s, d))
    assert np.abs(limbs).max() <= field.LIMB_BOUND


def test_invert(ab):
    a, _ = ab
    vals = [v for v in a if v % P != 0]
    out = _to_ints(_invert(_batch_fe(vals)))
    for x, o in zip(vals, out):
        assert (o * x) % P == 1


def test_pow22523(ab):
    a, _ = ab
    out = _to_ints(_pow22523(_batch_fe(a)))
    for x, o in zip(a, out):
        assert o % P == pow(x, (P - 5) // 8, P)


def test_freeze_canonical():
    vals = [0, 1, P - 1, P, P + 1, 2 * P + 5, 31 * P + 3, secrets.randbelow(P)]
    import jax.numpy as jnp

    arr = np.stack([pack.int_to_limbs(v, 20) for v in vals], axis=1)
    frozen = field.freeze(jnp.asarray(arr, dtype=jnp.int32))
    out = _to_ints(frozen)
    for v, o in zip(vals, out):
        assert o == v % P
        assert 0 <= o < P
    f = np.asarray(frozen)
    assert f.min() >= 0 and f.max() <= pack.MASK


def test_freeze_after_arithmetic(ab):
    a, b = ab
    out = _to_ints(_mulfreeze(_batch_fe(a), _batch_fe(b)))
    for x, y, o in zip(a, b, out):
        assert o == (x * y) % P


def test_sqrt_ratio():
    xs = [secrets.randbelow(P) for _ in range(6)]
    us = [(x * x) % P for x in xs]  # perfect squares with v=1
    ones = [1] * 6
    x_out, ok = _sqrt_ratio(_batch_fe(us), _batch_fe(ones))
    assert bool(np.asarray(ok).all())
    for u, o in zip(us, _to_ints(field.freeze(x_out))):
        assert (o * o) % P == u
    # non-residue: 2 is a non-square mod p iff ... pick u with no sqrt
    non_sq = []
    v = 2
    while len(non_sq) < 3:
        if pow(v, (P - 1) // 2, P) == P - 1:
            non_sq.append(v)
        v += 1
    _, ok = _sqrt_ratio(_batch_fe(non_sq), _batch_fe([1] * 3))
    assert not bool(np.asarray(ok).any())


def test_eq_mod_p():
    a = [5, 7, P - 1]
    b = [5 + 0, 7, P - 1]
    fa, fb = _batch_fe(a), _batch_fe(b)
    assert bool(np.asarray(field.eq_mod_p(fa, fb)).all())
    fc = _batch_fe([6, 7, 0])
    got = np.asarray(field.eq_mod_p(fa, fc))
    assert list(got) == [False, True, False]


def test_pack_roundtrip():
    raw = np.frombuffer(secrets.token_bytes(32 * 8), dtype=np.uint8).reshape(8, 32)
    limbs = pack.bytes_to_limbs_batch(raw)
    for i in range(8):
        want = int.from_bytes(raw[i].tobytes(), "little")
        assert pack.limbs_to_int(limbs[:, i]) == want


def test_lt_const():
    L = ref.L
    vals = [0, L - 1, L, L + 1, 2**256 - 1]
    arr = np.stack(
        [np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8) for v in vals]
    )
    got = pack.lt_const_le_batch(arr, L)
    assert list(got) == [True, True, False, False, False]


class TestKoggeStoneCarry:
    """The Kogge-Stone carry/borrow resolves (field._seq_carry/_cond_sub
    and their pallas twins) must match a plain sequential oracle across
    the full LIMB_BOUND input range, including all-propagate rows."""

    @staticmethod
    def _seq_carry_oracle(v):
        v = np.asarray(v)
        carry = np.zeros(v.shape[1], np.int64)
        out = np.zeros_like(v)
        for i in range(v.shape[0]):
            t = v[i].astype(np.int64) + carry
            carry = t >> field.BITS
            out[i] = t & field.MASK
        return out, carry

    @staticmethod
    def _cond_sub_oracle(v, c):
        v = np.asarray(v)
        t = (v - np.asarray(c)).astype(np.int64)
        borrow = np.zeros(t.shape[1], np.int64)
        out = np.zeros_like(t)
        for i in range(field.NLIMB):
            x = t[i] + borrow
            borrow = x >> field.BITS
            out[i] = x & field.MASK
        return np.where(borrow < 0, v, out)

    def _adversarial_batch(self, rng, lo, hi, b=96):
        v = rng.integers(lo, hi + 1, size=(field.NLIMB, b)).astype(np.int32)
        v[:, 0] = field.MASK   # all-propagate carries
        v[:, 1] = lo
        v[:, 2] = hi
        v[:, 3] = 0
        v[:, 4] = -1 if lo < 0 else 1
        return v

    def test_field_seq_carry_matches_oracle(self):
        rng = np.random.default_rng(11)
        bound = field.LIMB_BOUND
        v = self._adversarial_batch(rng, -bound, bound)
        got_l, got_c = field._seq_carry(jnp.asarray(v))
        ref_l, ref_c = self._seq_carry_oracle(v)
        assert (np.asarray(got_l) == ref_l).all()
        assert (np.asarray(got_c) == ref_c).all()

    def test_field_cond_sub_matches_oracle(self):
        rng = np.random.default_rng(12)
        v = rng.integers(0, field.MASK + 1,
                         size=(field.NLIMB, 96)).astype(np.int32)
        c = rng.integers(0, field.MASK + 1,
                         size=(field.NLIMB, 96)).astype(np.int32)
        v[:, 0] = c[:, 0]              # exact equality -> zero
        v[:, 1] = 0; c[:, 1] = field.MASK  # guaranteed underflow
        got = np.asarray(field._cond_sub(jnp.asarray(v), jnp.asarray(c)))
        assert (got == self._cond_sub_oracle(v, c)).all()

    def test_pallas_ops_carry_matches_oracle(self):
        from tendermint_tpu.crypto.jaxed25519.pallas_kernels import _make_ops

        ops = _make_ops(interpret=True)
        rng = np.random.default_rng(13)
        bound = field.LIMB_BOUND
        v = self._adversarial_batch(rng, -bound, bound)
        got_l, got_c = ops.seq_carry(jnp.asarray(v))
        ref_l, ref_c = self._seq_carry_oracle(v)
        assert (np.asarray(got_l) == ref_l).all()
        assert (np.asarray(got_c)[0] == ref_c).all()

    def test_freeze_canonicalizes_mod_p(self):
        rng = np.random.default_rng(14)
        bound = field.LIMB_BOUND
        v = self._adversarial_batch(rng, -bound, bound, b=32)
        got = np.asarray(field.freeze(jnp.asarray(v)))
        for col in range(v.shape[1]):
            want = sum(
                int(v[i, col]) << (field.BITS * i)
                for i in range(field.NLIMB)
            ) % ref.P
            have = sum(
                int(got[i, col]) << (field.BITS * i)
                for i in range(field.NLIMB)
            )
            assert have == want
            assert got[:, col].min() >= 0 and got[:, col].max() <= field.MASK
