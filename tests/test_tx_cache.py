"""TxCache LRU semantics (reference mempool/mempool.go:613-675
mapTxCache): dedupe, capacity eviction of the least-recently-used entry,
refresh-on-hit, explicit remove, reset.
"""

from tendermint_tpu.mempool.mempool import TxCache


def test_push_dedupes():
    c = TxCache(4)
    assert c.push(b"a")
    assert not c.push(b"a")
    assert c.push(b"b")


def test_capacity_evicts_lru():
    c = TxCache(3)
    for tx in (b"1", b"2", b"3"):
        assert c.push(tx)
    assert c.push(b"4")  # evicts b"1"
    assert c.push(b"1"), "oldest entry should have been evicted"
    # b"2" was evicted by re-adding b"1"; b"3"/b"4" remain cached
    assert not c.push(b"3")
    assert not c.push(b"4")


def test_hit_refreshes_recency():
    c = TxCache(3)
    for tx in (b"1", b"2", b"3"):
        c.push(tx)
    c.push(b"1")  # duplicate hit: refreshes b"1" to most-recent
    c.push(b"4")  # evicts b"2" (now the oldest), not b"1"
    assert not c.push(b"1")
    assert c.push(b"2")


def test_remove_and_reset():
    c = TxCache(4)
    c.push(b"x")
    c.remove(b"x")
    assert c.push(b"x"), "removed tx must be re-admittable"
    c.push(b"y")
    c.reset()
    assert c.push(b"x") and c.push(b"y")
    # removing an absent tx is a no-op
    c.remove(b"never-seen")
