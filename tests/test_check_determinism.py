"""scripts/check_determinism.py as a tier-1 guard (the static half of
the PR-15 determinism gate, wired like check_concurrency/check_metrics):
the analyzer must hold the consensus-critical tree at zero unsuppressed
findings, flag every seeded violation in the bad corpus, stay silent on
the disciplined corpus, keep its allowlist honest (shared machinery
with the concurrency gate: scripts/allowlist_util.py), and fit far
inside its ≤5s budget.

The fixes this gate locked in (each erased a real finding key — they
are fixed in code, NOT allowlisted):
  DT-ITER:...:ExecSession._stripe:builtin hash() — the sharded app's
    overlay striping was keyed by builtin hash(), which is
    PYTHONHASHSEED-randomized: stripe assignment (and every order
    derived from stripe walks) differed per process. Now crc32.
  (exec_promote stripe-walk ordering and _CommitBufferDB.flush
    insertion ordering are the runtime twins of the same bug — pinned
    byte-for-byte by tests/test_detcheck.py.)
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import check_concurrency as cc  # noqa: E402
import check_determinism as cd  # noqa: E402

BAD = os.path.join(REPO, "tests", "fixtures", "determinism_bad")
CLEAN = os.path.join(REPO, "tests", "fixtures", "determinism_clean")


def _run(paths, allowlist=None):
    return cd.run_check(paths, REPO, allowlist or {})


def test_tree_is_clean_under_allowlist():
    """The gate: zero unsuppressed findings on the consensus-critical
    modules, every suppression justified, nothing stale, and the scan
    fits the ≤5s acceptance budget with room."""
    allow = cd.load_allowlist(cd.DEFAULT_ALLOWLIST)
    assert allow, "allowlist should exist and be non-empty"
    t0 = time.time()
    findings, summary = _run([os.path.join(REPO, "tendermint_tpu")], allow)
    elapsed = time.time() - t0
    unsup = [f.key for f in findings if f.suppressed_by is None]
    assert unsup == [], f"unsuppressed findings: {unsup}"
    assert summary["stale_allowlist"] == [], (
        "allowlist entries with no matching finding — remove them: "
        f"{summary['stale_allowlist']}")
    assert summary["parse_errors"] == []
    assert summary["files"] >= 20, "critical-module scan looks truncated"
    assert elapsed < 5.0, f"checker took {elapsed:.1f}s (budget 5s)"


def test_fixed_finding_keys_stay_fixed():
    """The true positives this PR fixed must not resurface."""
    findings, _ = _run([os.path.join(REPO, "tendermint_tpu")])
    keys = {f.key for f in findings}
    fixed = ("DT-ITER:tendermint_tpu/abci/example/sharded_kvstore.py:"
             "ExecSession._stripe:builtin hash() (PYTHONHASHSEED-seeded)")
    assert fixed not in keys, f"fixed finding resurfaced: {fixed}"
    # no builtin-hash finding anywhere in the production tree
    assert not any("builtin hash()" in k for k in keys), (
        [k for k in keys if "builtin hash()" in k])


def test_bad_corpus_flags_every_rule():
    findings, summary = _run([BAD])
    assert summary["parse_errors"] == []
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.key)
    assert set(by_rule) == {"DT-CLOCK", "DT-RAND", "DT-ITER", "DT-ENV",
                            "DT-FLOAT", "DT-ID"}, by_rule
    keys = {f.key for f in findings}
    # the specific seeded shapes, by key
    assert ("DT-CLOCK:tests/fixtures/determinism_bad/bad_clock.py:"
            "StampingStore.put_row:time.time()->store db.set()") in keys
    assert ("DT-CLOCK:tests/fixtures/determinism_bad/bad_clock.py:"
            "StampingStore.snapshot_payload:datetime.utcnow()"
            "->serialize .pack()") in keys
    assert ("DT-RAND:tests/fixtures/determinism_bad/bad_rand.py:"
            "LotteryApp.deliver_tx:random.random()") in keys
    assert ("DT-RAND:tests/fixtures/determinism_bad/bad_rand.py:"
            "LotteryApp.shuffle_pool:unseeded Random()") in keys
    assert ("DT-RAND:tests/fixtures/determinism_bad/bad_rand.py:"
            "LotteryApp.sample_loop:random.sample()") in keys
    # import idioms must not bypass the source tables
    assert ("DT-RAND:tests/fixtures/determinism_bad/bad_rand.py:"
            "LotteryApp.aliased_draw:random.random()") in keys
    assert ("DT-RAND:tests/fixtures/determinism_bad/bad_rand.py:"
            "LotteryApp.bare_urandom:os.urandom()") in keys
    assert ("DT-CLOCK:tests/fixtures/determinism_bad/bad_clock.py:"
            "StampingStore.stamp_row:time.time()->store db.set()") in keys
    assert ("DT-ITER:tests/fixtures/determinism_bad/bad_iter.py:"
            "JournalFlusher.flush:loop->store db.set()") in keys
    assert ("DT-ITER:tests/fixtures/determinism_bad/bad_iter.py:"
            "JournalFlusher.stream:yield") in keys
    assert ("DT-ITER:tests/fixtures/determinism_bad/bad_iter.py:"
            "JournalFlusher.stream_direct:yield-from") in keys
    assert ("DT-ENV:tests/fixtures/determinism_bad/bad_env.py:"
            "EnvApp.subscript_read:os.environ[]") in keys
    assert ("DT-ITER:tests/fixtures/determinism_bad/bad_iter.py:"
            "HashStriper.route:builtin hash() "
            "(PYTHONHASHSEED-seeded)") in keys
    assert ("DT-ENV:tests/fixtures/determinism_bad/bad_env.py:"
            "EnvApp.begin_block:os.environ.get") in keys
    assert ("DT-ENV:tests/fixtures/determinism_bad/bad_env.py:"
            "EnvApp.node_tag:platform.node()") in keys
    assert ("DT-FLOAT:tests/fixtures/determinism_bad/bad_float.py:"
            "RewardApp.payout:int-truncation") in keys
    assert ("DT-FLOAT:tests/fixtures/determinism_bad/bad_float.py:"
            "RewardApp.store_share:float arithmetic"
            "->store db.set()") in keys
    assert ("DT-ID:tests/fixtures/determinism_bad/bad_id.py:"
            "SessionTagger.tag:id()") in keys


def test_clean_corpus_is_silent():
    findings, summary = _run([CLEAN])
    assert summary["parse_errors"] == []
    assert findings == [], [f.key for f in findings]


def test_allowlist_machinery_shared_with_concurrency_gate():
    """Satellite: both gates load suppressions through ONE helper
    (scripts/allowlist_util.py) — same justification enforcement, same
    stale-entry surfacing."""
    assert cd.load_allowlist is cc.load_allowlist


def test_allowlist_requires_justification(tmp_path):
    p = tmp_path / "allow.json"
    p.write_text(json.dumps(
        {"entries": [{"key": "DT-CLOCK:x:Y.z:w", "justification": ""}]}))
    with pytest.raises(ValueError, match="no justification"):
        cd.load_allowlist(str(p))
    p.write_text(json.dumps({"entries": [{"justification": "why"}]}))
    with pytest.raises(ValueError, match="no key"):
        cd.load_allowlist(str(p))


def test_stale_allowlist_entries_are_reported():
    findings, summary = _run(
        [CLEAN], {"DT-RAND:nonexistent:Thing.roll:random": "stale"})
    assert summary["stale_allowlist"] == [
        "DT-RAND:nonexistent:Thing.roll:random"]


def test_summary_counts_by_class():
    _findings, summary = _run([BAD])
    assert set(summary["by_class"]) == {"DT-CLOCK", "DT-RAND", "DT-ITER",
                                        "DT-ENV", "DT-FLOAT", "DT-ID"}
    assert sum(summary["by_class"].values()) == summary["findings"]
    assert summary["by_class_unsuppressed"] == summary["by_class"]


def test_json_baseline_mode():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_determinism.py"),
         "--json", "--allowlist", "", BAD],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["summary"]["unsuppressed"] == doc["summary"]["findings"] > 0
    rules = {f["rule"] for f in doc["findings"]}
    assert rules == {"DT-CLOCK", "DT-RAND", "DT-ITER", "DT-ENV",
                     "DT-FLOAT", "DT-ID"}


def test_parse_error_fails_gate(tmp_path):
    """An unparseable file means zero rules were checked on it — the
    gate must FAIL, not warn-and-pass."""
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    assert cd.main(["--allowlist", "", str(p)]) == 1


def test_cli_clean_tree_exit_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_determinism.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_sanctioned_escapes_stay_clean(tmp_path):
    """sorted()/.sort() launder order; set accumulation and membership
    are order-free; seeded Random and crc32 are pure functions."""
    p = tmp_path / "ok.py"
    p.write_text(
        "import random, zlib\n"
        "class C:\n"
        "    def __init__(self, db):\n"
        "        self.db = db\n"
        "        self.s = set()\n"
        "    def f(self):\n"
        "        for k in sorted(self.s):\n"
        "            self.db.set(k, b'1')\n"
        "        rows = [k for k in self.s]\n"
        "        rows.sort()\n"
        "        return rows\n"
        "    def g(self, seed, pool):\n"
        "        return random.Random(seed).choice(pool)\n"
        "    def h(self, key):\n"
        "        return zlib.crc32(key) % 8\n")
    findings = cd.analyze_file(str(p), "ok.py")
    assert findings == [], [f.key for f in findings]
    # a .set(...) STORE call is not a set() construction: iterating its
    # result must not read as set-iteration
    q = tmp_path / "store.py"
    q.write_text(
        "class D:\n"
        "    def commit_rows(self, db):\n"
        "        ok = db.set(b'k', b'v')\n"
        "        return list(ok or ())\n")
    findings = cd.analyze_file(str(q), "store.py")
    assert findings == [], [f.key for f in findings]


def test_doubly_nested_defs_analyzed_once(tmp_path):
    """A def nested inside a nested def produces exactly ONE finding,
    under its own parent's owner path — not one per ancestor scope
    (duplicate keys would make allowlisting impossible)."""
    p = tmp_path / "nested.py"
    p.write_text(
        "import time\n"
        "def outer():\n"
        "    def mid():\n"
        "        def deep():\n"
        "            return time.time()\n"
        "        return deep\n"
        "    return mid\n")
    findings = cd.analyze_file(str(p), "nested.py")
    keys = [f.key for f in findings]
    assert keys == ["DT-CLOCK:nested.py:outer.mid.deep:return"], keys


def test_lint_feeds_detcheck_debug_and_metrics():
    """Satellite: the static gate's results surface through the
    /debug/determinism bundle and the detlint_findings_total family."""
    from tendermint_tpu.metrics import prometheus_metrics
    from tendermint_tpu.tools import detcheck

    _findings, summary = _run([BAD])
    m = prometheus_metrics("detlint_test")
    detcheck.set_metrics(m.determinism)
    try:
        detcheck.record_lint(summary)
        rep = detcheck.report()
        assert rep["lint"]["findings"] == summary["findings"]
        assert rep["lint"]["unsuppressed"] == summary["unsuppressed"]
        assert set(rep["lint"]["by_class"]) == set(summary["by_class"])
        text = m.registry.render()
        assert "detlint_test_detlint_findings_total" in text
        assert 'cls="DT-RAND"' in text
    finally:
        detcheck.set_metrics(None)
        detcheck.reset_state()
