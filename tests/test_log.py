"""Structured logging (reference libs/cli/flags/log_level.go ParseLogLevel
+ log_level_test.go, libs/log/filter.go, tm_json_logger.go): per-module
levels, JSON format, config wiring.
"""

import io
import json
import logging
import os

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

import pytest

from tendermint_tpu.libs.log import (
    LEVELS,
    TMJSONFormatter,
    parse_log_level,
    setup_logging,
)


class TestParseLogLevel:
    def test_bare_level_means_star(self):
        assert parse_log_level("info") == {"*": logging.INFO}
        assert parse_log_level("debug") == {"*": logging.DEBUG}

    def test_module_pairs_with_star(self):
        got = parse_log_level("consensus:debug,mempool:debug,*:error")
        assert got == {
            "consensus": logging.DEBUG,
            "mempool": logging.DEBUG,
            "*": logging.ERROR,
        }

    def test_missing_star_uses_default(self):
        got = parse_log_level("state:debug", default="error")
        assert got == {"state": logging.DEBUG, "*": logging.ERROR}

    def test_none_level_squelches(self):
        got = parse_log_level("p2p:none,*:info")
        assert got["p2p"] > logging.CRITICAL

    @pytest.mark.parametrize("bad", [
        "",                       # empty (log_level.go:23-25)
        "state:debug,*:",         # empty level
        ":debug",                 # empty module
        "state:debug:extra",      # 3-part item
        "state:warn",             # unknown level name
        "state=debug",            # wrong separator
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            parse_log_level(bad)


class TestSetupLogging:
    def _fresh_loggers(self):
        # reset the module loggers this test touches so per-test state
        # doesn't leak through the global logging registry
        for name in ("tlog_state", "tlog_state.store", "tlog_p2p"):
            lg = logging.getLogger(name)
            lg.setLevel(logging.NOTSET)

    def test_per_module_filtering(self):
        self._fresh_loggers()
        buf = io.StringIO()
        setup_logging("tlog_state:debug,*:error", "plain", stream=buf)
        logging.getLogger("tlog_state.store").debug("child-debug-visible")
        logging.getLogger("tlog_p2p").info("default-info-squelched")
        logging.getLogger("tlog_p2p").error("default-error-visible")
        out = buf.getvalue()
        assert "child-debug-visible" in out       # hierarchy: state covers state.store
        assert "default-info-squelched" not in out
        assert "default-error-visible" in out

    def test_json_format_one_object_per_line(self):
        self._fresh_loggers()
        buf = io.StringIO()
        setup_logging("tlog_state:debug,*:error", "json", stream=buf)
        logging.getLogger("tlog_state").info("hello %s", "world")
        lines = [ln for ln in buf.getvalue().splitlines() if ln]
        assert len(lines) == 1
        obj = json.loads(lines[0])
        assert obj["msg"] == "hello world"
        assert obj["module"] == "tlog_state"
        assert obj["level"] == "info"
        assert "ts" in obj

    def test_json_exception_field(self):
        buf = io.StringIO()
        setup_logging("*:info", "json", stream=buf)
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            logging.getLogger("tlog_state").exception("failed")
        obj = json.loads(buf.getvalue().splitlines()[0])
        assert obj["level"] == "error"
        assert "boom" in obj["err"]

    def test_bad_format_raises(self):
        with pytest.raises(ValueError, match="log_format"):
            setup_logging("info", "yaml", stream=io.StringIO())

    def teardown_method(self):
        # restore a sane root so later tests' logging goes to stderr
        root = logging.getLogger()
        root.handlers[:] = []
        root.setLevel(logging.WARNING)


def test_config_carries_log_format_through_toml(tmp_path):
    from tendermint_tpu import config as cfg

    c = cfg.Config()
    c.base.log_level = "state:debug,*:error"
    c.base.log_format = "json"
    p = str(tmp_path / "config.toml")
    c.save(p)
    back = cfg.Config.load(p)
    assert back.base.log_level == "state:debug,*:error"
    assert back.base.log_format == "json"


def test_formatter_is_parseable_for_all_levels():
    fmt = TMJSONFormatter()
    for name, levelno in LEVELS.items():
        if name == "none":
            continue
        rec = logging.LogRecord(
            "mod", levelno if levelno else logging.INFO, "f.py", 1,
            "m%d", (7,), None,
        )
        obj = json.loads(fmt.format(rec))
        assert obj["msg"] == "m7"


def test_setup_logging_reconfiguration_resets_stale_module_levels():
    """A second setup_logging call must clear per-module overrides set by
    the first (config reload must not leave ghost levels)."""
    buf1 = io.StringIO()
    setup_logging("tlog_re:debug,*:error", "plain", stream=buf1)
    assert logging.getLogger("tlog_re").level == logging.DEBUG
    buf2 = io.StringIO()
    setup_logging("info", "plain", stream=buf2)
    assert logging.getLogger("tlog_re").level == logging.NOTSET
    logging.getLogger("tlog_re").info("now-visible-at-info")
    assert "now-visible-at-info" in buf2.getvalue()
    # restore
    root = logging.getLogger()
    root.handlers[:] = []
    root.setLevel(logging.WARNING)
