"""Network chaos engine unit tests (p2p/netchaos.py): fault-plan data
model and replayability, per-link decision determinism, the ChaosConn
write-path semantics, process-wide installation, and the switch hook.

The multi-node scenario suite built on this engine lives in
tests/test_scenarios.py (slow tier); everything here is fast and
socket-free except one tiny two-switch integration check.
"""

import os
import struct
import threading
import time

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

import pytest

from tendermint_tpu.metrics import prometheus_metrics
from tendermint_tpu.p2p import netchaos
from tendermint_tpu.p2p.netchaos import (
    ChaosConn,
    Decision,
    FaultPlan,
    LinkRule,
    NetChaosController,
)


@pytest.fixture(autouse=True)
def _no_leaked_controller():
    yield
    netchaos.uninstall()


# --- data model -------------------------------------------------------


class TestFaultPlan:
    def test_json_roundtrip_is_textual_identity(self):
        plan = FaultPlan(seed=7)
        plan.add(0, 5, netchaos.partition({"a"}, {"b", "c"}))
        plan.add(2, 9, netchaos.delay(0.1, jitter_s=0.05, srcs={"a"}))
        plan.add(1, 3, netchaos.throttle(1024))
        plan.add(0.5, 4, netchaos.disconnect_storm(0.2, dsts={"b"}))
        text = plan.to_json()
        again = FaultPlan.from_json(text)
        assert again.to_json() == text
        assert again.seed == 7
        assert len(again.phases) == 4

    def test_phase_windows(self):
        plan = FaultPlan().add(1, 2, netchaos.delay(0.1))
        assert plan.active(0.5) == []
        assert len(plan.active(1.0)) == 1
        assert len(plan.active(1.999)) == 1
        assert plan.active(2.0) == []
        assert plan.end_s() == 2.0
        with pytest.raises(ValueError):
            plan.add(3, 3, netchaos.delay(0.1))  # empty window

    def test_rule_validation(self):
        with pytest.raises(ValueError):
            LinkRule("nonsense")
        with pytest.raises(ValueError):
            LinkRule("drop", prob=1.5)


class TestLinkRuleMatching:
    def test_symmetric_partition_matches_both_directions(self):
        r = netchaos.partition({"a"}, {"b"})
        assert r.matches("a", "b")
        assert r.matches("b", "a")
        assert not r.matches("a", "c")
        assert not r.matches("c", "b")

    def test_one_way_drop_matches_one_direction(self):
        r = netchaos.one_way_drop({"a"}, {"b"})
        assert r.matches("a", "b")
        assert not r.matches("b", "a")

    def test_none_is_wildcard(self):
        r = LinkRule("delay", delay_s=0.1)
        assert r.matches("x", "y")
        r2 = LinkRule("delay", src={"x"}, delay_s=0.1, symmetric=False)
        assert r2.matches("x", "anyone")
        assert not r2.matches("anyone", "x")


# --- determinism ------------------------------------------------------


class TestDeterminism:
    def _stream(self, ctrl, src, dst, n=64):
        return [ctrl.outbound(src, dst, 100).drop for _ in range(n)]

    def test_same_seed_same_decision_stream(self):
        plan = FaultPlan(seed=42).add(0, 600, LinkRule("drop", prob=0.5))
        a = NetChaosController(plan)
        b = NetChaosController(plan)
        a.start()
        b.start()
        sa = self._stream(a, "x", "y")
        assert sa == self._stream(b, "x", "y")
        assert any(sa) and not all(sa)  # actually probabilistic

    def test_different_seed_differs(self):
        mk = lambda s: NetChaosController(  # noqa: E731
            FaultPlan(seed=s).add(0, 600, LinkRule("drop", prob=0.5)))
        assert self._stream(mk(1), "x", "y") != self._stream(mk(2), "x", "y")

    def test_other_links_do_not_perturb_a_links_stream(self):
        plan = FaultPlan(seed=9).add(0, 600, LinkRule("drop", prob=0.5))
        clean = NetChaosController(plan)
        noisy = NetChaosController(plan)
        want = self._stream(clean, "x", "y")
        got = []
        for i in range(64):
            noisy.outbound("p", "q", 1)  # concurrent link traffic
            noisy.outbound("q", "p", 1)
            got.append(noisy.outbound("x", "y", 100).drop)
        assert got == want

    def test_set_plan_resets_rng_streams(self):
        plan = FaultPlan(seed=5).add(0, 600, LinkRule("drop", prob=0.5))
        c = NetChaosController(plan)
        c.start()
        first = self._stream(c, "x", "y")
        c.set_plan(FaultPlan(seed=5).add(0, 600, LinkRule("drop", prob=0.5)))
        assert self._stream(c, "x", "y") == first


# --- decision semantics ----------------------------------------------


class _FakeConn:
    def __init__(self):
        self.written = []
        self.closed = False

    def write(self, data):
        self.written.append(bytes(data))

    def read_exact(self, n):
        return b"\x00" * n

    def close(self):
        self.closed = True


class TestChaosConn:
    def _link(self, rule, seed=1):
        plan = FaultPlan(seed=seed).add(0, 600, rule)
        ctrl = NetChaosController(plan)
        ctrl.start()
        raw = _FakeConn()
        return raw, ChaosConn(raw, ctrl, "src", "dst"), ctrl

    def test_drop_swallows_whole_writes(self):
        raw, conn, ctrl = self._link(netchaos.partition({"src"}, {"dst"}))
        conn.write(b"frame-1")
        conn.write(b"frame-2")
        assert raw.written == []
        assert ctrl.injected["drop"] == 2

    def test_unmatched_traffic_flows(self):
        raw, conn, ctrl = self._link(netchaos.partition({"a"}, {"b"}))
        conn.write(b"hello")
        assert raw.written == [b"hello"]
        assert ctrl.injected["drop"] == 0

    def test_disconnect_closes_and_raises(self):
        raw, conn, ctrl = self._link(netchaos.disconnect_storm(1.0))
        with pytest.raises(ConnectionError):
            conn.write(b"boom")
        assert raw.closed
        assert ctrl.injected["disconnect"] == 1

    def test_delay_is_bounded_and_counted(self):
        raw, conn, ctrl = self._link(netchaos.delay(0.01, jitter_s=0.01))
        t0 = time.perf_counter()
        conn.write(b"slow")
        took = time.perf_counter() - t0
        assert raw.written == [b"slow"]
        assert 0.005 < took < 1.0
        assert ctrl.injected["delay"] == 1
        # a mis-built plan cannot wedge the send routine for minutes
        d = Decision(delay_s=netchaos.MAX_INJECT_DELAY_S)
        assert d.delay_s <= netchaos.MAX_INJECT_DELAY_S

    def test_throttle_delivers_all_bytes(self):
        raw, conn, ctrl = self._link(netchaos.throttle(64 * 1024))
        payload = os.urandom(8192)
        conn.write(payload)
        assert b"".join(raw.written) == payload
        assert ctrl.injected["throttle"] == 1

    def test_read_side_passes_through(self):
        raw, conn, _ = self._link(netchaos.partition({"src"}, {"dst"}))
        assert conn.read_exact(4) == b"\x00" * 4  # inbound untouched

    def test_metrics_mirror(self):
        m = prometheus_metrics()
        plan = FaultPlan(seed=3).add(0, 600, netchaos.partition(None, None))
        ctrl = NetChaosController(plan, metrics=m.p2p)
        ctrl.start()
        ctrl.outbound("a", "b", 10)
        rendered = m.registry.render()
        assert 'tendermint_chaos_injected_total{kind="drop"} 1' in rendered
        assert "tendermint_chaos_active_rules 1" in rendered
        assert ctrl.injected["drop"] == 1


# --- installation + switch hook ---------------------------------------


class TestInstallation:
    def test_wrap_conn_identity_without_controller(self):
        raw = _FakeConn()
        assert netchaos.wrap_conn(raw, "a", "b") is raw

    def test_install_wrap_uninstall(self):
        ctrl = netchaos.install(NetChaosController(FaultPlan(seed=1)))
        assert netchaos.get_controller() is ctrl
        raw = _FakeConn()
        wrapped = netchaos.wrap_conn(raw, "a", "b")
        assert isinstance(wrapped, ChaosConn)
        netchaos.uninstall()
        assert netchaos.get_controller() is None
        assert netchaos.wrap_conn(raw, "a", "b") is raw


def _mk_switch(network="chaos-net"):
    from tendermint_tpu.crypto.keys import PrivKeyEd25519
    from tendermint_tpu.p2p import (
        MultiplexTransport,
        NodeInfo,
        NodeKey,
        ProtocolVersion,
        Switch,
    )
    from tendermint_tpu.p2p.base_reactor import ChannelDescriptor, Reactor

    class Echo(Reactor):
        def __init__(self):
            super().__init__("ECHO")
            self.got = []
            self.ev = threading.Event()

        def get_channels(self):
            return [ChannelDescriptor(id=0x77, priority=1)]

        def receive(self, ch_id, peer, msg_bytes):
            self.got.append(msg_bytes)
            self.ev.set()

        def start(self):
            pass

        def stop(self):
            pass

        def init_peer(self, peer):
            pass

        def add_peer(self, peer):
            pass

        def remove_peer(self, peer, reason):
            pass

    nk = NodeKey(PrivKeyEd25519.generate())
    ni = NodeInfo(
        protocol_version=ProtocolVersion(), id=nk.id, listen_addr="",
        network=network, version="dev", channels=bytes([0x77]),
        moniker="chaos-test")
    tr = MultiplexTransport(ni, nk)
    tr.listen("127.0.0.1:0")
    ni.listen_addr = tr.listen_addr
    sw = Switch(tr)
    echo = Echo()
    sw.add_reactor("ECHO", echo)
    sw.start()
    return sw, echo


class TestSwitchIntegration:
    def test_partition_blocks_then_heals_over_real_sockets(self):
        """Two real switches: with a partition rule armed between their
        ids, a broadcast never arrives; set an empty plan (heal) and
        the SAME connection delivers again — framing survives drops."""
        ctrl = netchaos.install(NetChaosController(FaultPlan(seed=11)))
        a = b = None
        try:
            a, echo_a = _mk_switch()
            b, echo_b = _mk_switch()
            peer = a.dial_peer(b.transport.listen_addr)
            assert peer is not None
            deadline = time.time() + 5
            while time.time() < deadline and b.peers.size() == 0:
                time.sleep(0.02)
            assert b.peers.size() == 1

            ctrl.set_plan(FaultPlan(seed=11).add(
                0, 600, netchaos.partition({a.node_info().id},
                                           {b.node_info().id})))
            a.broadcast(0x77, b"during-partition")
            assert not echo_b.ev.wait(0.6)
            assert echo_b.got == []
            assert ctrl.injected["drop"] >= 1

            ctrl.set_plan(FaultPlan(seed=11))  # heal
            echo_b.ev.clear()
            a.broadcast(0x77, b"after-heal")
            assert echo_b.ev.wait(5.0), "healed link never delivered"
            assert echo_b.got[-1] == b"after-heal"
        finally:
            for sw in (a, b):
                if sw is not None:
                    sw.stop()


class TestReconnectHygiene:
    def test_reconnect_attempts_metric_and_rate_limit(self, monkeypatch):
        """A dropped persistent peer's redials are counted per peer and
        spaced by the min-gap even with fast retry intervals."""
        from tendermint_tpu.p2p import switch as switch_mod

        monkeypatch.setattr(switch_mod, "RECONNECT_INTERVAL", 0.01)
        monkeypatch.setattr(switch_mod, "RECONNECT_MIN_GAP", 0.15)
        m = prometheus_metrics()
        a, _ = _mk_switch()
        a.metrics = m.p2p
        b, _ = _mk_switch()
        try:
            peer = a.dial_peer(b.transport.listen_addr,
                               expect_id=b.node_info().id, persistent=True)
            assert peer is not None
            b_id = b.node_info().id
            b.stop()  # kill the far side: reconnect loop starts
            a.stop_peer_for_error(peer, RuntimeError("injected drop"))
            time.sleep(0.8)
            rendered = m.registry.render()
            assert "p2p_reconnect_attempts_total" in rendered
            # rate limit: ~0.8s / 0.15s min gap -> at most ~6 attempts
            line = [ln for ln in rendered.splitlines()
                    if ln.startswith("tendermint_p2p_reconnect_attempts_total{")
                    and b_id in ln]
            assert line, rendered
            count = float(line[0].rsplit(" ", 1)[1])
            assert 1 <= count <= 7, line
        finally:
            a.stop()
