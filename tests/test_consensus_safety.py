"""Consensus safety boundaries: quorum strictness, polka-gated locking,
nil rounds, precommit equivocation — the remaining scenarios of the
reference's consensus/state_test.go family (TestStateFullRoundNil,
TestStateLockNoPOL polka gating, TestStateSlashingPrecommits) plus the
>2/3 commit boundary driven through the LIVE vote path.
"""

import os
import sys
import time

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

sys.path.insert(0, os.path.dirname(__file__))

from test_consensus_pol import Harness

from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
)


class TestFullRoundNil:
    def test_nil_round_advances_without_lock_or_commit(self):
        """No proposal ever arrives (the stub proposer stays silent):
        propose-timeout → we prevote nil; stubs prevote nil → we
        precommit nil; stubs precommit nil → round 1. Nothing locks,
        nothing commits (reference TestStateFullRoundNil)."""
        h = Harness(we_propose_first=False).start()
        try:
            pv0 = h.wait_our_vote(VOTE_TYPE_PREVOTE, 0, timeout=15)
            assert pv0.block_id.hash == b"", "must prevote nil without a proposal"
            h.stub_votes(VOTE_TYPE_PREVOTE, 0, BlockID())
            pc0 = h.wait_our_vote(VOTE_TYPE_PRECOMMIT, 0)
            assert pc0.block_id.hash == b""
            h.stub_votes(VOTE_TYPE_PRECOMMIT, 0, BlockID())
            h.wait_event(h.rounds, pred=lambda rs: rs.round == 1)
            assert h.cs.rs.locked_block is None
            assert h.cs.rs.height == 1, "nil round must not commit anything"
        finally:
            h.stop()


class TestPolkaGating:
    def test_no_lock_without_two_thirds_prevotes(self):
        """We propose B but the prevotes split 2-for-B / 2-nil (ours +
        stub 1 for B, stubs 2 and 3 nil): with all 4 votes in, 2/3-any
        is reached and prevote-wait fires, yet there is NO polka — we
        must precommit nil and must NOT lock. Locking on less than +2/3
        prevotes would be a safety violation (state.go:1044-1052
        requires the polka)."""
        h = Harness(we_propose_first=True).start()
        try:
            pv0 = h.wait_our_vote(VOTE_TYPE_PREVOTE, 0)
            assert pv0.block_id.hash
            h.stub_vote(1, VOTE_TYPE_PREVOTE, 0, pv0.block_id)
            h.stub_vote(2, VOTE_TYPE_PREVOTE, 0, BlockID())
            h.stub_vote(3, VOTE_TYPE_PREVOTE, 0, BlockID())
            pc0 = h.wait_our_vote(VOTE_TYPE_PRECOMMIT, 0, timeout=15)
            assert pc0.block_id.hash == b"", "precommit without polka must be nil"
            assert h.cs.rs.locked_block is None, "locked without +2/3 prevotes"
        finally:
            h.stop()


class TestCommitQuorumBoundary:
    def test_half_precommits_do_not_commit_third_does(self):
        """With 4 equal validators the commit threshold is 3 (>2/3 of 4).
        Ours + one stub precommit for B (2/4 = 50%) must NOT commit —
        assert no NewBlock and height unchanged over a real delay — and
        the third precommit must then commit immediately (the live-path
        equivalent of the VoteSet quorum math,
        types/vote_set.go:263 / validator_set.go:358-366)."""
        h = Harness(we_propose_first=True).start()
        try:
            pv0 = h.wait_our_vote(VOTE_TYPE_PREVOTE, 0)
            h.stub_votes(VOTE_TYPE_PREVOTE, 0, pv0.block_id)
            h.wait_our_vote(VOTE_TYPE_PRECOMMIT, 0)
            h.stub_vote(1, VOTE_TYPE_PRECOMMIT, 0, pv0.block_id)

            # 2 of 4 precommits: no commit may happen
            assert h.blocks.get(timeout=1.5) is None
            assert h.cs.rs.height == 1

            h.stub_vote(2, VOTE_TYPE_PRECOMMIT, 0, pv0.block_id)
            blk = h.wait_event(h.blocks)["block"]
            assert blk.header.height == 1
            assert blk.hash() == pv0.block_id.hash
        finally:
            h.stop()


class TestSlashingPrecommits:
    def test_conflicting_precommits_become_evidence(self):
        """A stub equivocates at the PRECOMMIT step (same round, two
        blocks) → DuplicateVoteEvidence with type=precommit lands in the
        evidence pool (reference TestStateSlashingPrecommits,
        state.go:1476-1482)."""
        h = Harness(we_propose_first=True).start()
        try:
            pv0 = h.wait_our_vote(VOTE_TYPE_PREVOTE, 0)
            h.stub_votes(VOTE_TYPE_PREVOTE, 0, pv0.block_id)
            h.wait_our_vote(VOTE_TYPE_PRECOMMIT, 0)

            h.stub_vote(1, VOTE_TYPE_PRECOMMIT, 0, pv0.block_id)
            alt, alt_parts = h.make_alt_block(1, txs=(b"equivocate-pc",))
            h.stub_vote(
                1, VOTE_TYPE_PRECOMMIT, 0,
                BlockID(hash=alt.hash(), parts_header=alt_parts.header()),
            )
            ev = h.wait_evidence()
            assert ev.vote_a.type == VOTE_TYPE_PRECOMMIT
            assert ev.vote_a.block_id != ev.vote_b.block_id
        finally:
            h.stop()
