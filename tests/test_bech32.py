"""bech32 (reference libs/bech32/bech32.go + bech32_test.go, BIP-173
test vectors)."""

import hashlib

import pytest

from tendermint_tpu.libs.bech32 import (
    convert_and_encode,
    decode,
    decode_and_convert,
    encode,
)


def test_roundtrip_shasum():
    """reference bech32_test.go TestEncodeAndDecode."""
    digest = hashlib.sha256(b"hello world\n").digest()
    bech = convert_and_encode("shasum", digest)
    hrp, data = decode_and_convert(bech)
    assert hrp == "shasum"
    assert data == digest


# BIP-173 valid test vectors (public specification)
@pytest.mark.parametrize("valid", [
    "A12UEL5L",
    "an83characterlonghumanreadablepartthatcontainsthenumber1andtheexcludedcharactersbio1tt5tgs",
    "abcdef1qpzry9x8gf2tvdw0s3jn54khce6mua7lmqqqxw",
    "11qqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqc8247j",
    "split1checkupstagehandshakeupstreamerranterredcaperred2y9e3w",
])
def test_bip173_valid_vectors(valid):
    hrp, data = decode(valid)
    # re-encoding canonicalizes to lowercase and round-trips
    assert encode(hrp, data) == valid.lower()


@pytest.mark.parametrize("invalid", [
    "pzry9x0s0muk",        # no separator
    "1pzry9x0s0muk",       # empty hrp
    "x1b4n0q5v",           # invalid data char
    "li1dgmt3",            # too-short checksum
    "A1G7SGD8",            # checksum error
    "10a06t8",             # empty hrp (separator first)
    "1qzzfhee",            # empty hrp
    "abcdef1Qpzry9x8gf2tvdw0s3jn54khce6mua7lmqqqxw",  # mixed case
    "an84characterslonghumanreadablepartthatcontainsthenumber1andtheexcludedcharactersbio1569pvx",  # >90 chars
])
def test_bip173_invalid_vectors(invalid):
    with pytest.raises(ValueError):
        decode(invalid)


def test_convert_bits_strict_unpad_rejects_nonzero_padding():
    from tendermint_tpu.libs.bech32 import convert_bits

    with pytest.raises(ValueError):
        convert_bits([0b11111], 5, 8, False)  # leftover non-zero bits


def test_roundtrip_various_lengths():
    for n in (0, 1, 19, 20, 32, 33):
        payload = bytes(range(n % 256))[:n] or b""
        bech = convert_and_encode("tm", payload)
        hrp, back = decode_and_convert(bech)
        assert (hrp, back) == ("tm", payload)
