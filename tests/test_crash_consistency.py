"""Crash-consistency engine tests: the seeded storage-fault injector
(libs/storagechaos.py), FileDB crash-tail hygiene, privval atomic
persistence, tx-index recovery, the kvstore family's atomic Commit, and
the kill/restart recovery matrix (tools/crashmatrix.py).

Tier-1 runs the unit layer plus the single-fault FAST_CASES subset
(~≤30s); the full crash-point × fault-mode matrix, the multi-process
SIGKILL localnet scenario, and the bench line are slow-marked."""

import json
import os
import struct
import time

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

import pytest

# a simulated process death unwinds node threads with
# SimulatedCrashError by design — that is the crash, not a test bug
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")

from tendermint_tpu.libs import fail
from tendermint_tpu.libs import storagechaos as sc
from tendermint_tpu.libs.db import FileDB, MemDB
from tendermint_tpu.tools import crashmatrix


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    fail.reset()


# --- fault plan -------------------------------------------------------


class TestFaultPlan:
    def test_json_roundtrip(self):
        plan = (sc.StorageFaultPlan(seed=42)
                .add("wal", "torn_write", 3)
                .add("db:tx_index", "partial_batch", 7))
        plan2 = sc.StorageFaultPlan.from_json(plan.to_json())
        assert plan2.to_json() == plan.to_json()
        assert plan2.seed == 42
        assert plan2.faults[1].target == "db:tx_index"

    def test_validation(self):
        with pytest.raises(ValueError):
            sc.StorageFault("wal", "nope", 0)
        with pytest.raises(ValueError):
            sc.StorageFault("walrus", "torn_write", 0)
        with pytest.raises(ValueError):
            sc.StorageFault("wal", "torn_write", -1)

    def test_per_fault_rng_deterministic(self):
        plan = sc.StorageFaultPlan(seed=9).add("wal", "torn_write", 1)
        f = plan.faults[0]
        a = [plan.rng_for(f).randrange(1000) for _ in range(3)]
        b = [plan.rng_for(f).randrange(1000) for _ in range(3)]
        assert a == b

    def test_seed_derivation_is_process_independent(self):
        """Pinned sha256 derivation: builtin hash() is salted per
        process (PYTHONHASHSEED) and would break cross-process replay
        of a failing matrix cell."""
        assert sc._derive_seed("9|wal|torn_write|1") == int.from_bytes(
            __import__("hashlib").sha256(
                b"9|wal|torn_write|1").digest()[:8], "big")
        # and the value a given plan draws is a stable constant
        plan = sc.StorageFaultPlan(seed=9).add("wal", "torn_write", 1)
        assert plan.rng_for(plan.faults[0]).randrange(10**6) == \
            __import__("random").Random(
                sc._derive_seed("9|wal|torn_write|1")).randrange(10**6)


# --- FaultyDB against FileDB ------------------------------------------


def _filedb(tmp_path, name="t"):
    return FileDB(str(tmp_path / f"{name}.db"))


def _run_ops_until_crash(db):
    """Feed numbered set() ops until the injector kills the process;
    returns how many completed."""
    done = 0
    try:
        for i in range(100):
            db.set(b"k%03d" % i, b"v%03d" % i)
            done += 1
    except sc.SimulatedCrashError:
        return done
    raise AssertionError("fault never fired")


class TestFaultyDB:
    def test_torn_write_reload_drops_tail_and_truncates(self, tmp_path):
        plan = sc.StorageFaultPlan(seed=1).add("db:t", "torn_write", 5)
        inj = sc.StorageFaultInjector(plan)
        db = sc.FaultyDB(_filedb(tmp_path), inj, "db:t")
        assert _run_ops_until_crash(db) == 5
        assert inj.dead
        db.close()
        path = str(tmp_path / "t.db")
        torn_size = os.path.getsize(path)
        re = FileDB(path)
        # the 5 whole records parse; the torn prefix is dropped AND cut
        # off the file so later appends stay reachable
        assert re.tail_dropped_bytes > 0
        assert os.path.getsize(path) < torn_size
        for i in range(5):
            assert re.get(b"k%03d" % i) == b"v%03d" % i
        assert re.get(b"k005") is None
        # append-after-tear regression: new records written after the
        # reload must survive ANOTHER reload (pre-hygiene they were
        # buried behind the torn bytes and lost)
        re.set(b"post", b"tear")
        re.close()
        re2 = FileDB(path)
        assert re2.get(b"post") == b"tear"
        assert re2.tail_dropped_bytes == 0
        re2.close()

    def test_partial_batch_applies_strict_prefix(self, tmp_path):
        plan = sc.StorageFaultPlan(seed=3).add("db:t", "partial_batch", 0)
        inj = sc.StorageFaultInjector(plan)
        db = sc.FaultyDB(_filedb(tmp_path), inj, "db:t")
        ops = [("set", b"b%02d" % i, b"x%02d" % i) for i in range(20)]
        with pytest.raises(sc.SimulatedCrashError):
            db.apply_batch(ops)
        db.close()
        re = FileDB(str(tmp_path / "t.db"))
        n = sum(1 for _ in re.iterator(b"b", b"c"))
        assert n < 20  # strict prefix
        # the surviving prefix is contiguous from op 0
        for i in range(n):
            assert re.get(b"b%02d" % i) == b"x%02d" % i
        re.close()

    def test_lost_tail_truncates_to_last_fsync(self, tmp_path):
        plan = sc.StorageFaultPlan(seed=4).add("db:t", "lost_tail", 6)
        inj = sc.StorageFaultInjector(plan)
        db = sc.FaultyDB(_filedb(tmp_path), inj, "db:t")
        for i in range(4):
            db.set(b"s%d" % i, b"v")
        db.sync()  # durable floor: 4 records
        with pytest.raises(sc.SimulatedCrashError):
            for i in range(10):
                db.set(b"u%d" % i, b"v")
        db.close()
        re = FileDB(str(tmp_path / "t.db"))
        for i in range(4):
            assert re.get(b"s%d" % i) == b"v"  # fsync'd: survives
        assert not list(re.iterator(b"u", b"v"))  # un-synced tail: gone
        re.close()

    def test_bit_flip_reload_never_raises(self, tmp_path):
        plan = sc.StorageFaultPlan(seed=5).add("db:t", "bit_flip", 3)
        inj = sc.StorageFaultInjector(plan)
        db = sc.FaultyDB(_filedb(tmp_path), inj, "db:t")
        _run_ops_until_crash(db)
        db.close()
        re = FileDB(str(tmp_path / "t.db"))  # must not raise
        assert inj.injected["bit_flip"] == 1
        re.close()

    def test_same_seed_same_durable_bytes(self, tmp_path):
        def run(sub):
            plan = sc.StorageFaultPlan(seed=77).add("db:t", "torn_write", 4)
            inj = sc.StorageFaultInjector(plan)
            d = tmp_path / sub
            d.mkdir()
            db = sc.FaultyDB(_filedb(d), inj, "db:t")
            _run_ops_until_crash(db)
            db.close()
            with open(d / "t.db", "rb") as f:
                return f.read()

        assert run("a") == run("b")

    def test_dead_injector_freezes_all_writes(self, tmp_path):
        inj = sc.StorageFaultInjector()
        db = sc.FaultyDB(_filedb(tmp_path), inj, "db:t")
        db.set(b"a", b"1")
        inj.kill()
        for op in (lambda: db.set(b"b", b"2"),
                   lambda: db.delete(b"a"),
                   lambda: db.apply_batch([("set", b"c", b"3")]),
                   lambda: db.sync()):
            with pytest.raises(sc.SimulatedCrashError):
                op()
        db.close()
        re = FileDB(str(tmp_path / "t.db"))
        assert re.get(b"a") == b"1"
        assert re.get(b"b") is None
        re.close()

    def test_memdb_partial_batch_prefix(self):
        plan = sc.StorageFaultPlan(seed=6).add("db:m", "partial_batch", 0)
        inj = sc.StorageFaultInjector(plan)
        mem = MemDB()
        db = sc.FaultyDB(mem, inj, "db:m")
        with pytest.raises(sc.SimulatedCrashError):
            db.apply_batch([("set", b"p%d" % i, b"v") for i in range(10)])
        n = sum(1 for _ in mem.iterator(b"p", b"q"))
        assert n < 10


# --- FileDB crash-tail hygiene (no injector) --------------------------


class TestFileDBTailHygiene:
    def test_manual_torn_record_and_garbage_op(self, tmp_path):
        path = str(tmp_path / "h.db")
        db = FileDB(path)
        db.set(b"good", b"val")
        db.close()
        with open(path, "ab") as f:
            f.write(struct.pack(">BII", 1, 100, 100) + b"short")
        re = FileDB(path)
        assert re.get(b"good") == b"val"
        assert re.tail_dropped_bytes == 9 + 5
        re.close()
        # garbage op byte stops the parse at the last whole record
        with open(path, "ab") as f:
            f.write(struct.pack(">BII", 9, 1, 1) + b"kv")
        re2 = FileDB(path)
        assert re2.get(b"good") == b"val"
        assert re2.tail_dropped_bytes > 0
        assert "tail_dropped_bytes" in re2.stats()
        re2.close()

    def test_absurd_length_header_stops_clean(self, tmp_path):
        path = str(tmp_path / "h2.db")
        db = FileDB(path)
        db.set(b"k", b"v")
        db.close()
        with open(path, "ab") as f:
            f.write(struct.pack(">BII", 1, FileDB.MAX_RECORD_FIELD + 1, 0))
        re = FileDB(path)
        assert re.get(b"k") == b"v"
        re.close()


# --- WAL: crash tail vs corruption ------------------------------------


class TestWALFaults:
    def _wal(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL, EndHeightMessage

        wal = WAL(str(tmp_path / "wal" / "wal"))
        wal.start()
        return wal, EndHeightMessage

    def test_torn_record_is_silent_crash_tail(self, tmp_path):
        wal, End = self._wal(tmp_path)
        plan = sc.StorageFaultPlan(seed=8).add("wal", "torn_write", 1)
        inj = sc.StorageFaultInjector(plan)
        sc.wrap_wal(wal, inj)
        wal.write_sync(End(1))
        with pytest.raises(sc.SimulatedCrashError):
            wal.write_sync(End(2))
        wal.group.close()
        from tendermint_tpu.consensus.wal import WAL

        re = WAL(str(tmp_path / "wal" / "wal"))
        msgs = list(re.iter_messages())
        # boot marker + height 1; the torn tail is NOT corruption
        assert [m.height for m in msgs] == [0, 1]
        assert re.corrupted_records == 0

    def test_bit_flip_is_counted_corruption(self, tmp_path):
        wal, End = self._wal(tmp_path)
        plan = sc.StorageFaultPlan(seed=9).add("wal", "bit_flip", 1)
        inj = sc.StorageFaultInjector(plan)
        sc.wrap_wal(wal, inj)
        wal.write_sync(End(1))
        with pytest.raises(sc.SimulatedCrashError):
            wal.write_sync(End(2))
        wal.group.close()
        from tendermint_tpu.consensus.wal import WAL

        re = WAL(str(tmp_path / "wal" / "wal"))
        list(re.iter_messages())
        assert re.corrupted_records == 1  # CRC/garbage-header detected


# --- privval ----------------------------------------------------------


class TestPrivvalAtomicity:
    def test_save_is_atomic_unique_tempfile(self, tmp_path):
        from tendermint_tpu.privval import FilePV

        path = str(tmp_path / "pv.json")
        pv = FilePV.generate(path)
        pv.last_height = 7
        pv.save()
        # a crashed writer's torn tempfile next to the target must not
        # matter: the target itself is always a complete document
        with open(str(tmp_path / ".tmp-privval-dead"), "w") as f:
            f.write('{"torn":')
        re = FilePV.load(path)
        assert re.last_height == 7
        assert not os.path.exists(path + ".tmp")  # fixed-name tmp is gone

    def test_crash_before_save_keeps_old_guard(self, tmp_path):
        from tendermint_tpu.crypto.keys import PrivKeyEd25519
        from tendermint_tpu.privval import FilePV
        from tendermint_tpu.types.basic import (VOTE_TYPE_PRECOMMIT,
                                                VOTE_TYPE_PREVOTE, BlockID,
                                                PartSetHeader, Vote)

        path = str(tmp_path / "pv.json")
        pv = FilePV(PrivKeyEd25519.generate(), path)
        v1 = Vote(validator_address=pv.get_address(), validator_index=0,
                  height=5, round=0, type=VOTE_TYPE_PREVOTE,
                  block_id=BlockID(b"h" * 32, PartSetHeader(1, b"p" * 32)),
                  timestamp=time.time_ns())
        pv.sign_vote("chain", v1)
        assert v1.signature

        def _boom(name):
            raise sc.SimulatedCrashError(name)

        fail.arm_crash("Privval.BeforeSignStateSave", action=_boom)
        v2 = Vote(validator_address=pv.get_address(), validator_index=0,
                  height=6, round=0, type=VOTE_TYPE_PRECOMMIT,
                  block_id=BlockID(b"i" * 32, PartSetHeader(1, b"p" * 32)),
                  timestamp=time.time_ns())
        with pytest.raises(sc.SimulatedCrashError):
            pv.sign_vote("chain", v2)
        # the signature was never persisted NOR released: the on-disk
        # guard still says height 5, and no caller holds v2's signature
        re = FilePV.load(path)
        assert (re.last_height, re.last_round, re.last_step) == (5, 0, 2)

    def test_torn_write_injected_tmp_never_corrupts_target(self, tmp_path):
        """Regression with the torn-write injector: tear the persisted
        FILE mid-save by crashing between tempfile write and replace —
        simulated by pointing save at a dead injector via the harness
        wrapper — then reload."""
        from tendermint_tpu.privval import FilePV

        path = str(tmp_path / "pv.json")
        pv = FilePV.generate(path)
        pv.last_height = 3
        pv.save()
        size_before = os.path.getsize(path)
        inj = sc.StorageFaultInjector()
        rec = crashmatrix._RecordingPV(pv, inj,
                                       str(tmp_path / "ledger"))
        inj.kill()
        from tendermint_tpu.types.basic import (VOTE_TYPE_PREVOTE, BlockID,
                                                PartSetHeader, Vote)

        v = Vote(validator_address=pv.get_address(), validator_index=0,
                 height=9, round=0, type=VOTE_TYPE_PREVOTE,
                 block_id=BlockID(b"x" * 32, PartSetHeader(1, b"p" * 32)),
                 timestamp=time.time_ns())
        with pytest.raises(sc.SimulatedCrashError):
            rec.sign_vote("chain", v)
        assert os.path.getsize(path) == size_before
        assert FilePV.load(path).last_height == 3


# --- tx index marker + recovery ---------------------------------------


class TestIndexRecovery:
    def test_marker_written_last_and_reloaded(self):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.state.txindex import KVTxIndexer, TxResult

        db = MemDB()
        idx = KVTxIndexer(db)
        idx.index_batch(3, [TxResult(
            height=3, index=0, tx=b"t1",
            result=abci.ResponseDeliverTx(code=0))])
        assert idx.indexed_height() == 3
        # a fresh indexer over the same db reads the durable marker
        assert KVTxIndexer(db).indexed_height() == 3

    def test_torn_batch_loses_marker_with_tail(self, tmp_path):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.state.txindex import KVTxIndexer, TxResult

        plan = sc.StorageFaultPlan(seed=10).add("db:idx", "torn_write", 0)
        inj = sc.StorageFaultInjector(plan)
        db = sc.FaultyDB(_filedb(tmp_path, "idx"), inj, "db:idx")
        idx = KVTxIndexer(db)
        results = [TxResult(height=2, index=i, tx=b"tx%d" % i,
                            result=abci.ResponseDeliverTx(code=0))
                   for i in range(6)]
        with pytest.raises(sc.SimulatedCrashError):
            idx.index_batch(2, results)
        db.close()
        re = KVTxIndexer(FileDB(str(tmp_path / "idx.db")))
        # marker rides LAST in the batch: any tear strands the block
        # below it, so the block reads as not-ingested and recovery
        # re-indexes it whole
        assert re.indexed_height() == 0

    def test_advance_marker(self):
        from tendermint_tpu.state.txindex import KVTxIndexer

        db = MemDB()
        idx = KVTxIndexer(db)
        idx.advance_marker(9)
        assert idx.indexed_height() == 9
        idx.advance_marker(4)  # never regresses
        assert idx.indexed_height() == 9
        assert KVTxIndexer(db).indexed_height() == 9

    def test_per_tx_marker_stays_one_block_behind(self):
        """[tx_index] batch=false regression: per-tx ingest cannot know
        when a block completes, so the DURABLE marker must not claim
        the in-flight block — a crash after tx 0 of block h would
        otherwise mark h fully ingested and recovery would skip its
        missing tail. Live indexed_height() keeps reporting progress."""
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.state.txindex import KVTxIndexer, TxResult

        db = MemDB()
        idx = KVTxIndexer(db)
        for i in range(3):
            idx.index(TxResult(height=4, index=i, tx=b"t%d" % i,
                               result=abci.ResponseDeliverTx(code=0)))
        assert idx.indexed_height() == 4  # live progress
        # a fresh instance trusts only the durable floor: block 4 must
        # be re-checked by recovery even though all its txs landed
        assert KVTxIndexer(db).indexed_height() == 3

    def test_legacy_dir_without_marker_seeds_from_rows(self):
        """Pre-marker data dirs must not trigger a whole-chain
        re-index at boot: the floor seeds from the existing height tag
        rows (minus one for the possibly-half-ingested top block)."""
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.state.txindex import KVTxIndexer, TxResult

        db = MemDB()
        idx = KVTxIndexer(db)
        for h in (2, 3, 11):
            idx.index_batch(h, [TxResult(
                height=h, index=0, tx=b"t%d" % h,
                result=abci.ResponseDeliverTx(code=0))])
        db.delete(KVTxIndexer._META_HEIGHT)  # simulate a legacy dir
        assert KVTxIndexer(db).indexed_height() == 10


# --- kvstore atomic commit --------------------------------------------


class TestAppCommitAtomicity:
    def test_writes_invisible_in_backing_until_commit(self):
        from tendermint_tpu.abci.example.kvstore import KVStoreApplication

        backing = MemDB()
        app = KVStoreApplication(backing)
        app.deliver_tx(b"a=1")
        assert app.db.get(b"kv:a") == b"1"  # app-visible
        assert backing.get(b"kv:a") is None  # not durable yet
        app.commit()
        assert backing.get(b"kv:a") == b"1"

    def test_crashed_block_replays_identically_nonidempotent(self):
        """inc: is a read-modify-write — pre-buffer, a crash mid-block
        left the bump durable and the replay double-applied it."""
        from tendermint_tpu.abci.example.sharded_kvstore import (
            ShardedKVStoreApplication)

        backing = MemDB()
        app = ShardedKVStoreApplication(backing)
        app.deliver_tx(b"inc:c")
        app.commit()
        h1 = app.app_hash
        # block 2 executes (bump to 2) but the process dies pre-commit
        app.deliver_tx(b"inc:c")
        app2 = ShardedKVStoreApplication(backing)  # "restart"
        assert app2.height == 1
        assert app2.db.get(b"kv:c") == b"1"  # zero trace of the block
        app2.deliver_tx(b"inc:c")  # replay
        app2.commit()
        assert app2.db.get(b"kv:c") == b"2"
        assert app2.app_hash != h1

    def test_churn_epoch_batch_replays_identically_after_crash(self):
        """The crash-matrix find: EndBlock's rotation batch is a
        read-modify-write over the phantom pool — a crashed-then-
        replayed epoch must emit the SAME batch."""
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.abci.example.kvstore import (
            ChurnKVStoreApplication)

        def fresh(backing):
            return ChurnKVStoreApplication(backing, epoch_blocks=1,
                                           rotation_fraction=0.5,
                                           phantom_pool=4, seed=5)

        backing = MemDB()
        app = fresh(backing)
        app.init_chain(abci.RequestInitChain(validators=[
            abci.ValidatorUpdate(pub_key=b"\x01" * 32, power=100)]))
        app.begin_block(abci.RequestBeginBlock())
        batch1 = app.end_block(
            abci.RequestEndBlock(height=1)).validator_updates
        # crash before commit: a fresh app over the same backing must
        # reproduce batch1 exactly (nothing of the first run leaked)
        app2 = fresh(backing)
        app2.init_chain(abci.RequestInitChain(validators=[
            abci.ValidatorUpdate(pub_key=b"\x01" * 32, power=100)]))
        app2.begin_block(abci.RequestBeginBlock())
        batch2 = app2.end_block(
            abci.RequestEndBlock(height=1)).validator_updates
        assert ([(u.pub_key, u.power) for u in batch1]
                == [(u.pub_key, u.power) for u in batch2])

    def test_speculation_promote_leaves_backing_untouched(self):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.abci.example.sharded_kvstore import (
            ShardedKVStoreApplication)

        backing = MemDB()
        app = ShardedKVStoreApplication(backing)
        s = app.exec_open(1)
        app.exec_begin_block(s, abci.RequestBeginBlock())
        app.exec_deliver_tx(s, 0, b"spec=1")
        app.exec_end_block(s, abci.RequestEndBlock(height=1))
        app.exec_promote(s)
        # promoted ≠ committed: zero durable trace until app Commit
        assert app.db.get(b"kv:spec") == b"1"
        assert backing.get(b"kv:spec") is None
        app.commit()
        assert backing.get(b"kv:spec") == b"1"


# --- statesync mid-restore crash --------------------------------------


class TestStatesyncMidChunkCrash:
    def test_partial_restore_leaves_app_state_intact(self):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.abci.example.kvstore import KVStoreApplication

        producer = KVStoreApplication()
        producer.snapshot_interval = 1
        producer.deliver_tx(b"s1=v1")
        producer.deliver_tx(b"s2=v2")
        producer.snapshot_chunk_size = 8  # force several chunks
        producer.commit()
        snap, chunks = next(iter(producer._snapshots.values()))
        assert snap.chunks >= 2

        restorer = KVStoreApplication()
        restorer.deliver_tx(b"mine=kept")
        restorer.commit()
        h_before, hash_before = restorer.height, restorer.app_hash
        r = restorer.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=snap, app_hash=producer.app_hash))
        assert r.result == abci.OFFER_ACCEPT
        # apply all but the final chunk, then "crash" (restore state
        # simply dies with the process)
        for i in range(snap.chunks - 1):
            res = restorer.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(index=i, chunk=chunks[i]))
            assert res.result == abci.APPLY_ACCEPT
        # pre-restore state untouched: the payload installs only after
        # the FINAL chunk validates
        assert restorer.height == h_before
        assert restorer.app_hash == hash_before
        assert restorer.db.get(b"kv:mine") == b"kept"

    def test_midchunk_fail_point_aborts_restore_cleanly(self):
        """The Statesync.MidChunkApply point exists on the apply loop;
        a hook raising there surfaces as a failed restore candidate
        (fallback path), never a half-installed app."""
        calls = []

        def hook():
            calls.append(1)
            raise ValueError("injected mid-chunk crash")

        fail.set_hook("Statesync.MidChunkApply", hook)
        with pytest.raises(ValueError):
            fail.fail_point("Statesync.MidChunkApply")
        assert calls == [1]
        src = open(os.path.join(
            os.path.dirname(fail.__file__), "..", "statesync",
            "restore.py")).read()
        assert 'fail_point("Statesync.MidChunkApply")' in src


# --- config / metrics / monitor ---------------------------------------


class TestTelemetry:
    def test_storage_config_toml_roundtrip(self):
        from tendermint_tpu import config as cfg

        c = cfg.Config()
        c.storage.fault_plan = "plans/crash.json"
        c.storage.fault_seed = 13
        c2 = cfg.Config.from_toml(c.to_toml())
        assert c2.storage.fault_plan == "plans/crash.json"
        assert c2.storage.fault_seed == 13

    def test_chaos_section_still_a_dataclass(self):
        """Regression: inserting [storage] must not steal [chaos]'s
        @dataclass decorator — its keys have to keep round-tripping."""
        from tendermint_tpu import config as cfg

        c = cfg.Config()
        c.chaos.enable = True
        c.chaos.seed = 5
        chaos_toml = c.to_toml().split("[chaos]")[1].split("[")[0]
        assert "enable = true" in chaos_toml and "seed = 5" in chaos_toml
        c2 = cfg.Config.from_toml(c.to_toml())
        assert c2.chaos.enable is True and c2.chaos.seed == 5
        assert cfg.ChaosConfig(enable=True).enable

    def test_recovery_metric_families_registered(self):
        from tendermint_tpu.metrics import prometheus_metrics

        m = prometheus_metrics()
        body = m.registry.render()
        for fam in ("tendermint_recovery_replayed_blocks_total",
                    "tendermint_recovery_time_seconds",
                    "tendermint_storage_faults_injected_total"):
            assert fam in body
        m.recovery.storage_faults.with_labels("torn_write").inc()
        body = m.registry.render()
        assert 'kind="torn_write"' in body

    def test_injector_reports_to_metric(self):
        from tendermint_tpu.metrics import prometheus_metrics

        m = prometheus_metrics()
        plan = sc.StorageFaultPlan(seed=2).add("db:m", "partial_batch", 0)
        inj = sc.StorageFaultInjector(plan)
        inj.set_metrics(m.recovery.storage_faults)
        db = sc.FaultyDB(MemDB(), inj, "db:m")
        with pytest.raises(sc.SimulatedCrashError):
            db.apply_batch([("set", b"a", b"1"), ("set", b"b", b"2")])
        body = m.registry.render()
        assert ('tendermint_storage_faults_injected_total'
                '{kind="partial_batch"} 1') in body

    def test_monitor_recovery_view_and_corruption_health(self):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from tendermint_tpu.tools.monitor import (HEALTH_FULL,
                                                  HEALTH_MODERATE, Monitor)

        payloads = {
            "/debug/consensus": {
                "height": 5, "dwell_s": 0.1, "threshold_s": 30.0,
                "stalls_total": 0, "stalls": [], "live": {"peers": []},
            },
            "/debug/recovery": {
                "handshake_outcome": "ok", "replayed_blocks": 2,
                "replay_from": 3, "replay_to": 4,
                "reindexed_blocks": 1, "recovery_time_s": 0.8,
                "wal_corrupted_records": 0,
            },
        }

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps(payloads.get(self.path, {})).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        daddr = "%s:%d" % srv.server_address[:2]
        try:
            mon = Monitor(["rpc"], debug_addrs=[daddr])
            ns = mon.nodes["rpc"]
            ns.mark_online()
            ns.height = 5
            mon._poll_debug(ns, daddr)
            assert ns.recovered
            assert (ns.replayed_blocks, ns.replay_from, ns.replay_to,
                    ns.reindexed_blocks) == (2, 3, 4, 1)
            snap = mon.snapshot()["nodes"][0]
            assert snap["recovered"] and snap["replayed_blocks"] == 2
            # a recovered boot alone is informational, not degraded
            assert mon.health() == HEALTH_FULL
            # live WAL corruption degrades health
            payloads["/debug/recovery"]["wal_corrupted_records"] = 3
            mon._poll_debug(ns, daddr)
            assert ns.wal_corrupting
            assert mon.health() == HEALTH_MODERATE
            ns.clear_debug_view()
            assert ns.wal_corrupted == 0 and not ns.recovered
        finally:
            srv.shutdown()
            srv.server_close()


# --- fail.py named arming ---------------------------------------------


class TestNamedFailPoints:
    def test_arm_crash_nth_and_action(self):
        hits = []

        def action(name):
            hits.append(name)

        fail.arm_crash("X.Y", nth=3, action=action)
        for _ in range(5):
            fail.fail_point("X.Y")
        assert hits == ["X.Y"]  # fired exactly once, at the 3rd hit

    def test_env_point_spelling(self, monkeypatch):
        fail.reset()
        monkeypatch.setenv("FAIL_TEST_POINT", "A.B:2")
        hits = []
        fail.arm_crash("noop", action=lambda n: None)  # keep armed dict hot
        # _ensure_env_point arms A.B at 2nd hit with the DEFAULT action
        # (os._exit) — swap the action after arming to observe it
        fail.fail_point("other")
        fail._armed["A.B"][1] = lambda n: hits.append(n)
        fail.fail_point("A.B")
        fail.fail_point("A.B")
        assert hits == ["A.B"]

    def test_known_points_are_wired(self):
        """Every KNOWN_POINT name appears in exactly the module that
        owns it — the matrix enumerates this list, so a renamed or
        dropped call site must fail loudly here."""
        import tendermint_tpu

        root = os.path.dirname(tendermint_tpu.__file__)
        blob = ""
        for sub in ("consensus/state.py", "state/execution.py",
                    "state/parallel.py", "state/txindex.py",
                    "mempool/mempool.py", "privval/file_pv.py",
                    "statesync/restore.py"):
            blob += open(os.path.join(root, sub)).read()
        for point in fail.KNOWN_POINTS:
            assert f'fail_point("{point}")' in blob, point


# --- the matrix -------------------------------------------------------


@pytest.mark.parametrize("point,mode", crashmatrix.FAST_CASES)
def test_crash_matrix_fast(tmp_path, point, mode):
    """The tier-1 single-fault subset: one representative crash point
    per subsystem + the two storage-fault modes with dedicated
    recovery machinery (WAL crash tail, torn index batch)."""
    res = crashmatrix.run_case(str(tmp_path / "home"), point, mode=mode)
    assert res["ok"], res


_FULL_ONLY = [c for c in crashmatrix.full_cases()
              if c not in crashmatrix.FAST_CASES]


@pytest.mark.slow
@pytest.mark.parametrize("point,mode", _FULL_ONLY)
def test_crash_matrix_full(tmp_path, point, mode):
    """Every crash point × fault mode (the acceptance grid); each cell
    replayable bit-for-bit from (point, nth, mode, seed)."""
    res = crashmatrix.run_case(str(tmp_path / "home"), point, mode=mode)
    assert res["ok"], res


@pytest.mark.slow
def test_localnet_crash_scenario(tmp_path):
    """Multi-process SIGKILL suite: real subprocesses over kernel
    sockets; kill mid-commit, restart, converge with safety_ok."""
    from tendermint_tpu.tools import scenarios

    res = scenarios.run("localnet_crash", tmp_root=str(tmp_path))
    assert res["ok"], res
    assert res["safety_ok"]
    assert res["recoveries"][0]["handshake_outcome"] in ("ok", "")


@pytest.mark.slow
def test_bench_crashrecovery_schema():
    """`bench.py crashrecovery` emits one standard BENCH line with an
    oracle-gated positive latency."""
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TM_TPU_CRYPTO_BACKEND="cpu", TM_TPU_WARMUP="0",
               TM_TPU_BENCH_CRASHREC_ROUNDS="2")
    out = subprocess.run(
        [sys.executable, "bench.py", "crashrecovery"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("{")][-1]
    o = json.loads(line)
    assert o["metric"].startswith("crash_recovery_kill_to_committing")
    assert o["unit"] == "ms"
    assert o["value"] > 0, o
    assert all(r["oracle_ok"] for r in o["rounds"])
