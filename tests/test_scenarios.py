"""Chaos/churn scenario suite (tools/scenarios.py) + churn-driver unit
tests.

The six named scenarios each boot a real in-process localnet and are
slow-marked (tens of seconds each, and multi-node nets are exactly the
load-flake class tier-1 must not carry); the churn-driver tests are
fast and tier-1. `bench.py chaosnet` runs partition_heal with the same
oracle and reports recovery latency.
"""

import os

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
os.environ.setdefault("TM_TPU_WARMUP", "0")

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import ChurnKVStoreApplication
from tendermint_tpu.libs.db import MemDB


# --- churn driver (fast, tier-1) --------------------------------------


def _drive(app, heights, txs=()):
    """Run begin/deliver/end/commit for each height; returns the
    end_block validator-update batches per height."""
    batches = []
    for h in heights:
        app.begin_block(abci.RequestBeginBlock())
        for tx in txs:
            app.deliver_tx(tx)
        res = app.end_block(abci.RequestEndBlock(height=h))
        app.commit()
        batches.append(list(res.validator_updates))
    return batches


def _seed_real_validators(app, n=4, power=10):
    from tendermint_tpu.crypto import pubkey_to_bytes
    from tendermint_tpu.crypto.keys import PrivKeyEd25519

    vals = []
    for i in range(n):
        pk = PrivKeyEd25519.gen_from_secret(b"real-%d" % i).pub_key()
        vals.append(abci.ValidatorUpdate(
            pub_key=pubkey_to_bytes(pk), power=power))
    app.init_chain(abci.RequestInitChain(validators=vals))
    return vals


class TestChurnDriver:
    def test_epoch_batches_are_deterministic_from_seed(self):
        runs = []
        for _ in range(2):
            app = ChurnKVStoreApplication(MemDB(), epoch_blocks=2,
                                          rotation_fraction=0.5,
                                          phantom_pool=6, seed=99)
            _seed_real_validators(app)
            runs.append(_drive(app, range(1, 11)))
        assert runs[0] == runs[1], "same seed must rotate identically"
        # and a different seed rotates differently
        app = ChurnKVStoreApplication(MemDB(), epoch_blocks=2,
                                      rotation_fraction=0.5,
                                      phantom_pool=6, seed=100)
        _seed_real_validators(app)
        assert _drive(app, range(1, 11)) != runs[0]

    def test_epochs_only_on_boundaries_and_batches_are_large(self):
        app = ChurnKVStoreApplication(MemDB(), epoch_blocks=3,
                                      phantom_pool=8, seed=1)
        _seed_real_validators(app)
        batches = _drive(app, range(1, 10))
        for i, batch in enumerate(batches, start=1):
            if i % 3 == 0:
                assert batch, f"epoch boundary {i} emitted nothing"
            else:
                assert batch == [], f"non-boundary {i} emitted updates"
        assert app.epochs_run == 3
        # first boundary fills the pool in one large batch
        assert len(batches[2]) == 8

    def test_liveness_bound_holds_across_epochs(self):
        """Real validators keep > 2/3 of total power no matter how many
        epochs run — phantoms can never threaten quorum."""
        app = ChurnKVStoreApplication(MemDB(), epoch_blocks=1,
                                      rotation_fraction=0.5,
                                      phantom_pool=32, seed=7)
        _seed_real_validators(app, n=4, power=10)
        _drive(app, range(1, 16))
        phantom = sum(p for _, p in app._phantoms())
        real = app._real_power()
        assert real == 40
        assert 3 * real > 2 * (real + phantom), (real, phantom)

    def test_rotation_actually_rotates(self):
        app = ChurnKVStoreApplication(MemDB(), epoch_blocks=1,
                                      rotation_fraction=0.5,
                                      phantom_pool=6, seed=3)
        _seed_real_validators(app)
        _drive(app, [1])
        first = {pk for pk, _ in app._phantoms()}
        _drive(app, [2, 3])
        later = {pk for pk, _ in app._phantoms()}
        assert first != later
        assert first & later, "rotation should keep some survivors"

    def test_tx_driven_updates_still_ride_along(self):
        from tendermint_tpu.crypto import pubkey_to_bytes
        from tendermint_tpu.crypto.keys import PrivKeyEd25519

        app = ChurnKVStoreApplication(MemDB(), epoch_blocks=2, seed=5)
        _seed_real_validators(app)
        newk = PrivKeyEd25519.gen_from_secret(b"txval").pub_key()
        tx = b"val:" + pubkey_to_bytes(newk).hex().encode() + b"!9"
        app.begin_block(abci.RequestBeginBlock())
        app.deliver_tx(tx)
        res = app.end_block(abci.RequestEndBlock(height=2))
        pks = [u.pub_key for u in res.validator_updates]
        assert pubkey_to_bytes(newk) in pks  # tx update present
        assert len(res.validator_updates) > 1  # epoch batch rode along

    def test_proxy_creator_spec_parsing(self):
        from tendermint_tpu.proxy import default_client_creator

        creator = default_client_creator(
            "churn_kvstore:epoch=3,frac=0.25,pool=5,seed=11")
        # local client creator returns a client wrapping the app
        client = creator()
        target = getattr(client, "app", None) or getattr(
            client, "_app", None)
        assert target is not None
        assert target.epoch_blocks == 3
        assert target.rotation_fraction == 0.25
        assert target.phantom_pool == 5
        assert target.seed == 11
        with pytest.raises(ValueError):
            default_client_creator("churn_kvstore:bogus=1")


# --- the named scenarios (slow: real multi-node localnets) ------------


def _run(name, **kw):
    from tendermint_tpu.tools import scenarios

    res = scenarios.run(name, **kw)
    assert res["ok"], res
    return res


@pytest.mark.slow
def test_scenario_partition_heal():
    res = _run("partition_heal")
    assert "partition_suspected" in res["stall_reasons"]
    assert res["recovery_s"] > 0
    assert res["injected"]["disconnect"] > 0


@pytest.mark.slow
def test_scenario_asym_partition():
    res = _run("asym_partition")
    assert res["recovery_s"] > 0


@pytest.mark.slow
def test_scenario_delay_jitter():
    res = _run("delay_jitter")
    assert res["progressed_under_delay"]
    assert res["injected"]["delay"] > 0


@pytest.mark.slow
def test_scenario_churn_storm():
    res = _run("churn_storm")
    assert res["epochs_run"] > 0
    assert res["disconnects"] > 0


@pytest.mark.slow
def test_scenario_rotation_epoch():
    res = _run("rotation_epoch")
    assert res["valsets_agree"]
    assert res["valset_size"] > 4


@pytest.mark.slow
def test_scenario_statesync_join_under_churn(tmp_path):
    res = _run("statesync_join_under_churn", tmp_root=str(tmp_path))
    assert res["restored_base"] > 1


@pytest.mark.slow
def test_scenario_fault_timeline_replays_from_seed():
    """Same seed => byte-identical fault plan AND the same injected
    drop pattern on a fixed synthetic packet schedule (the netchaos
    determinism contract at scenario level)."""
    from tendermint_tpu.p2p import netchaos

    def timeline(seed):
        plan = netchaos.FaultPlan(seed=seed)
        plan.add(0, 5, netchaos.LinkRule("drop", prob=0.4))
        plan.add(1, 6, netchaos.delay(0.01, jitter_s=0.05))
        ctrl = netchaos.NetChaosController(plan, time_fn=lambda: 0.0)
        ctrl.start()
        ctrl._time = lambda: 2.0  # inside both phases
        return plan.to_json(), [
            (d.drop, round(d.delay_s, 9))
            for d in (ctrl.outbound("a", "b", 100) for _ in range(200))
        ]

    assert timeline(1234) == timeline(1234)
    assert timeline(1234) != timeline(4321)
