"""GenesisDoc + ConsensusParams matrix (reference types/genesis.go,
types/params.go): validation errors, JSON round trip, ABCI param
updates, params hash sensitivity.
"""

import os

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

import pytest

from tendermint_tpu.crypto import keys
from tendermint_tpu.types.genesis import (
    MAX_CHAIN_ID_LEN,
    BlockSizeParams,
    ConsensusParams,
    EvidenceParams,
    GenesisDoc,
    GenesisValidator,
)

PK = keys.PrivKeyEd25519.gen_from_secret(b"genesis").pub_key()


def _doc(**kw):
    base = dict(
        chain_id="gen-chain",
        validators=[GenesisValidator(PK, 10, "v0")],
        app_hash=b"\x01\x02",
        app_state={"k": "v"},
    )
    base.update(kw)
    return GenesisDoc(**base)


def test_validate_matrix():
    _doc().validate_and_complete()
    with pytest.raises(ValueError, match="chain_id"):
        _doc(chain_id="").validate_and_complete()
    with pytest.raises(ValueError, match="chain_id length"):
        _doc(chain_id="c" * (MAX_CHAIN_ID_LEN + 1)).validate_and_complete()
    with pytest.raises(ValueError, match="zero voting power"):
        _doc(validators=[GenesisValidator(PK, 0)]).validate_and_complete()

    bad_params = ConsensusParams(BlockSizeParams(max_bytes=0))
    with pytest.raises(ValueError, match="max_bytes"):
        _doc(consensus_params=bad_params).validate_and_complete()
    too_big = ConsensusParams(BlockSizeParams(max_bytes=104857600 + 1))
    with pytest.raises(ValueError, match="max_bytes"):
        _doc(consensus_params=too_big).validate_and_complete()
    bad_ev = ConsensusParams(evidence=EvidenceParams(max_age=0))
    with pytest.raises(ValueError, match="max_age"):
        _doc(consensus_params=bad_ev).validate_and_complete()


def test_json_round_trip():
    doc = _doc()
    back = GenesisDoc.from_json(doc.to_json())
    assert back.chain_id == doc.chain_id
    assert back.app_hash == doc.app_hash
    assert back.app_state == {"k": "v"}
    assert len(back.validators) == 1
    assert back.validators[0].power == 10
    assert back.validators[0].pub_key.address() == PK.address()
    assert back.consensus_params.hash() == doc.consensus_params.hash()
    # an empty-validator genesis is allowed at load (validators may come
    # from the app via InitChain) and round-trips
    empty = GenesisDoc.from_json(_doc(validators=[]).to_json())
    assert empty.validators == []


def test_from_json_rejects_invalid():
    doc = _doc(chain_id="ok")
    broken = doc.to_json().replace('"ok"', '""')
    with pytest.raises(ValueError, match="chain_id"):
        GenesisDoc.from_json(broken)


def test_save_load_file(tmp_path):
    p = str(tmp_path / "genesis.json")
    doc = _doc()
    doc.save(p)
    assert GenesisDoc.load(p).chain_id == doc.chain_id


def test_params_update_and_hash():
    base = ConsensusParams()
    assert base.update(None).hash() == base.hash()

    class _BS:
        max_bytes = 1024
        max_gas = 55

    class _EV:
        max_age = 7

    class _Upd:
        block_size = _BS
        evidence = _EV

    upd = base.update(_Upd)
    assert upd.block_size.max_bytes == 1024
    assert upd.block_size.max_gas == 55
    assert upd.evidence.max_age == 7
    assert upd.hash() != base.hash()
    # original untouched (update is copy-on-write)
    assert base.block_size.max_bytes == 22020096

    class _Partial:
        block_size = None
        evidence = _EV

    part = base.update(_Partial)
    assert part.block_size.max_bytes == base.block_size.max_bytes
    assert part.evidence.max_age == 7
