"""Metrics tests (reference per-package metrics.go + node/node.go
Prometheus listener): primitive rendering, and a live node exposing
consensus/mempool metrics at /metrics.
"""

import os
import time
import urllib.request

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.libs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsServer,
    Registry,
)


def test_counter_gauge_render():
    r = Registry()
    c = r.counter("test_total", "a counter")
    c.inc()
    c.inc(2)
    g = r.gauge("test_height", "a gauge", ("chain",))
    g.with_labels("main").set(7)
    out = r.render()
    assert "# TYPE test_total counter" in out
    assert "test_total 3" in out
    assert 'test_height{chain="main"} 7' in out


def test_histogram_render():
    r = Registry()
    h = r.histogram("test_secs", "timings", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    out = r.render()
    assert 'test_secs_bucket{le="0.1"} 1' in out
    assert 'test_secs_bucket{le="1"} 2' in out
    assert 'test_secs_bucket{le="+Inf"} 3' in out
    assert "test_secs_count 3" in out


def test_metrics_server():
    r = Registry()
    r.gauge("up", "is up").set(1)
    srv = MetricsServer(r, "127.0.0.1", 0)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://{srv.listen_addr}/metrics") as resp:
            body = resp.read().decode()
        assert "up 1" in body
    finally:
        srv.stop()


def test_node_prometheus_endpoint(tmp_path):
    from test_node import init_files, make_config

    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK,
        query_for_event,
    )

    c = make_config(tmp_path, "n0")
    c.instrumentation.prometheus = True
    c.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
    init_files(c)
    node = default_new_node(c)
    sub = node.event_bus.subscribe("t", query_for_event(EVENT_NEW_BLOCK), 16)
    node.start()
    try:
        h = 0
        deadline = time.time() + 30
        while h < 2 and time.time() < deadline:
            m = sub.get(timeout=1.0)
            if m is not None:
                h = m.data["block"].header.height
        assert h >= 2
        addr = node._metrics_server.listen_addr
        with urllib.request.urlopen(f"http://{addr}/metrics") as resp:
            body = resp.read().decode()
        # consensus height tracked and >= 2
        line = next(
            l for l in body.splitlines()
            if l.startswith("tendermint_consensus_height "))
        assert float(line.split()[-1]) >= 2
        assert "tendermint_consensus_validators 1" in body
        assert "tendermint_state_block_processing_time_count" in body
        assert "tendermint_mempool_size" in body
    finally:
        node.stop()
