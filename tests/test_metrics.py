"""Metrics tests (reference per-package metrics.go + node/node.go
Prometheus listener): primitive rendering, and a live node exposing
consensus/mempool metrics at /metrics.
"""

import os
import time
import urllib.request

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.libs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsServer,
    Registry,
)


def test_counter_gauge_render():
    r = Registry()
    c = r.counter("test_total", "a counter")
    c.inc()
    c.inc(2)
    g = r.gauge("test_height", "a gauge", ("chain",))
    g.with_labels("main").set(7)
    out = r.render()
    assert "# TYPE test_total counter" in out
    assert "test_total 3" in out
    assert 'test_height{chain="main"} 7' in out


def test_histogram_render():
    r = Registry()
    h = r.histogram("test_secs", "timings", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    out = r.render()
    assert 'test_secs_bucket{le="0.1"} 1' in out
    assert 'test_secs_bucket{le="1"} 2' in out
    assert 'test_secs_bucket{le="+Inf"} 3' in out
    assert "test_secs_count 3" in out


def test_metrics_server():
    r = Registry()
    r.gauge("up", "is up").set(1)
    srv = MetricsServer(r, "127.0.0.1", 0)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://{srv.listen_addr}/metrics") as resp:
            body = resp.read().decode()
        assert "up 1" in body
    finally:
        srv.stop()


def test_node_prometheus_endpoint(tmp_path):
    from test_node import init_files, make_config

    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK,
        query_for_event,
    )

    c = make_config(tmp_path, "n0")
    c.instrumentation.prometheus = True
    c.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
    init_files(c)
    node = default_new_node(c)
    sub = node.event_bus.subscribe("t", query_for_event(EVENT_NEW_BLOCK), 16)
    node.start()
    try:
        h = 0
        deadline = time.time() + 30
        while h < 2 and time.time() < deadline:
            m = sub.get(timeout=1.0)
            if m is not None:
                h = m.data["block"].header.height
        assert h >= 2
        addr = node._metrics_server.listen_addr
        with urllib.request.urlopen(f"http://{addr}/metrics") as resp:
            body = resp.read().decode()
        # consensus height tracked and >= 2
        line = next(
            l for l in body.splitlines()
            if l.startswith("tendermint_consensus_height "))
        assert float(line.split()[-1]) >= 2
        assert "tendermint_consensus_validators 1" in body
        assert "tendermint_state_block_processing_time_count" in body
        assert "tendermint_mempool_size" in body
    finally:
        node.stop()


def test_crypto_and_step_metrics_exposition_golden():
    """Exposition-format golden test for the observability families:
    exact line shapes for the CryptoMetrics set and the consensus
    step-duration histogram, as a Prometheus scraper sees them."""
    from tendermint_tpu.metrics import prometheus_metrics

    m = prometheus_metrics("tm")
    m.crypto.batch_verify_seconds.with_labels("jax").observe(0.002)
    m.crypto.batch_size.observe(64)
    m.crypto.signatures_verified.inc(63)
    m.crypto.signatures_invalid.inc(1)
    m.crypto.routing_decisions.with_labels("device").inc()
    m.crypto.device_transfer_seconds.set(0.0004)
    m.crypto.device_compute_seconds.set(0.0016)
    m.consensus.step_duration.with_labels("propose").observe(0.01)

    out = m.registry.render()
    for line in (
        "# TYPE tm_crypto_batch_verify_seconds histogram",
        'tm_crypto_batch_verify_seconds_bucket{backend="jax",le="0.0025"} 1',
        'tm_crypto_batch_verify_seconds_bucket{backend="jax",le="+Inf"} 1',
        'tm_crypto_batch_verify_seconds_count{backend="jax"} 1',
        "# TYPE tm_crypto_batch_size histogram",
        'tm_crypto_batch_size_bucket{le="64"} 1',
        "tm_crypto_batch_size_count 1",
        "# TYPE tm_crypto_signatures_verified_total counter",
        "tm_crypto_signatures_verified_total 63",
        "tm_crypto_signatures_invalid_total 1",
        'tm_crypto_batch_routing_total{route="device"} 1',
        "# TYPE tm_crypto_device_transfer_seconds gauge",
        "tm_crypto_device_transfer_seconds 0.0004",
        "tm_crypto_device_compute_seconds 0.0016",
        "# TYPE tm_consensus_step_duration_seconds histogram",
        'tm_consensus_step_duration_seconds_bucket{step="propose",le="0.01"} 1',
        'tm_consensus_step_duration_seconds_count{step="propose"} 1',
    ):
        assert line in out, f"missing exposition line: {line}"
    # labeled families with no children render no samples at all
    assert "tm_crypto_batch_routing_total 0" not in out
    assert "tm_consensus_step_duration_seconds_count 0" not in out


def test_nop_metrics_accept_observability_calls():
    """nop_metrics() must swallow every new telemetry call for free —
    instrumentation-off nodes take these code paths on every block."""
    from tendermint_tpu.metrics import nop_metrics

    m = nop_metrics()
    m.crypto.batch_verify_seconds.with_labels("cpu").observe(0.1)
    m.crypto.batch_size.observe(8)
    m.crypto.signatures_verified.inc(8)
    m.crypto.routing_decisions.with_labels("cpu").inc()
    m.crypto.device_transfer_seconds.set(0.0)
    m.consensus.step_duration.with_labels("commit").observe(0.1)
