"""Fleet-level causal tracing (tools/fleettrace.py) and the exec-lane
flight recorder (state/parallel.py):

- NTP-style clock-offset probe: min-RTT selection, uncertainty = RTT/2,
  early exit on a crisp probe
- golden 4-node stitch: known ±offsets, a two-hop relay, a straggler
  validator — exact offsets, propagation edges, stage waterfall, and
  100% attribution recovered from synthetic timeline records
- missing-marks honesty: dropped quorum marks become unaccounted time
  (coverage drops), never misattributed to a neighboring stage
- commit-stage splice parsing from a Prometheus exposition body
- chrome_trace / summarize exports
- FleetTrace collector against injected fetchers: common-height
  intersection, offset recovery, metrics splice, JSONL history
- FlightRecorder unit behavior (rings, percentiles, metrics sink)
- tier-1 provider contract: every /debug/* provider answers
  JSON-serializable, schema-stable payloads in validator AND replica
  modes, including /debug/exec and /debug/clock
- monitor --history JSONL sink
- slow: the proptrace scenario oracle end-to-end (live localnet over
  real HTTP with ±0.5s injected skews)
"""

import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts"))

from test_node import init_files, make_config

from tendermint_tpu.tools import fleettrace


# --- clock-offset probe ------------------------------------------------


def test_probe_offset_min_rtt_wins():
    """Three probes with RTTs 10/2/30ms and per-probe true offsets
    0.4/0.5/0.6s: the crisp middle probe must win, so the estimate is
    exactly its offset with uncertainty RTT/2."""
    times = iter([0.0, 0.010, 10.0, 10.002, 20.0, 20.030])
    clocks = iter([0.005 + 0.4, 10.001 + 0.5, 20.015 + 0.6])

    def clock_fn():
        return {"wall_s": next(clocks), "identity": {"node_id": "abc"}}

    est = fleettrace.probe_offset(
        clock_fn, repeats=3, now_fn=lambda: next(times))
    assert est["offset_s"] == pytest.approx(0.5)
    assert est["rtt_s"] == pytest.approx(0.002)
    assert est["uncertainty_s"] == pytest.approx(0.001)
    assert est["probes"] == 3
    assert est["identity"]["node_id"] == "abc"


def test_probe_offset_good_rtt_early_exit():
    times = iter([0.0, 0.010, 10.0, 10.002, 20.0, 20.030])
    clocks = iter([0.005, 10.001, 20.015])
    est = fleettrace.probe_offset(
        lambda: {"wall_s": next(clocks)}, repeats=5,
        now_fn=lambda: next(times), good_rtt_s=0.005)
    assert est["probes"] == 2  # second probe was crisp enough
    assert est["rtt_s"] == pytest.approx(0.002)


# --- golden stitch -----------------------------------------------------

# fleet-clock truth for the golden height: proposer n0 emits at
# T0+10ms, n1 hears it from n0 at +20ms, n2 from n1 at +30ms (hop 2),
# n3 from n0 at +40ms; quorums at +60/+80ms, commit +90ms, apply
# +100ms. Every node stores marks on its OWN skewed clock.
_T0 = 100.0
_OFFSETS = {"n0": 0.5, "n1": -0.5, "n2": 0.25, "n3": 0.0}


def _tl(marks, votes=None):
    return {"marks": marks, "votes": votes or {}, "max_round": 0,
            "rounds_seen": [0], "round_entries": {"0": 1},
            "re_entries": 0}


def _mark(fleet_t, offset, peer_id=""):
    return {"t": fleet_t + offset, "peer_id": peer_id}


def _golden_nodes():
    o0, o1, o2, o3 = (_OFFSETS[n] for n in ("n0", "n1", "n2", "n3"))
    n0 = {
        "name": "n0", "node_id": "id0", "offset_s": o0,
        "uncertainty_s": 0.0005,
        "timeline": _tl(
            {
                "new_height": _mark(_T0, o0),
                "proposal_emit": _mark(_T0 + 0.010, o0),
                "prevote_23": _mark(_T0 + 0.060, o0),
                "precommit_23": _mark(_T0 + 0.080, o0),
                "commit": _mark(_T0 + 0.090, o0),
                "apply_block": _mark(_T0 + 0.100, o0),
            },
            votes={"prevote": {
                "0": _mark(_T0 + 0.020, o0),
                "1": _mark(_T0 + 0.030, o0, "id1"),
                "2": _mark(_T0 + 0.035, o0, "id2"),
                "3": _mark(_T0 + 0.055, o0, "id3"),
            }}),
    }
    n1 = {
        "name": "n1", "node_id": "id1", "offset_s": o1,
        "uncertainty_s": 0.0005,
        "timeline": _tl(
            {"proposal_received": _mark(_T0 + 0.020, o1, "id0")}),
    }
    n2 = {
        "name": "n2", "node_id": "id2", "offset_s": o2,
        "uncertainty_s": 0.0005,
        "timeline": _tl(
            {"proposal_received": _mark(_T0 + 0.030, o2, "id1")}),
    }
    n3 = {
        "name": "n3", "node_id": "id3", "offset_s": o3,
        "uncertainty_s": 0.0005,
        "timeline": _tl(
            {"proposal_received": _mark(_T0 + 0.040, o3, "id0")}),
    }
    return [n0, n1, n2, n3]


def test_golden_four_node_stitch():
    nodes = _golden_nodes()
    rec = fleettrace.stitch_height(9, nodes)
    assert rec is not None
    assert rec["height"] == 9
    assert rec["reference"] == "collector"

    # offsets echoed per node
    for name, off in _OFFSETS.items():
        assert rec["offsets"][name]["offset_s"] == pytest.approx(off)

    # propagation tree: proposer n0; n2 heard it via n1 (hop 2)
    tree = rec["tree"]
    assert tree["proposer"] == "n0"
    assert [e["to"] for e in tree["edges"]] == ["n1", "n2", "n3"]
    by_to = {e["to"]: e for e in tree["edges"]}
    assert by_to["n1"]["from"] == "n0" and by_to["n1"]["hop"] == 1
    assert by_to["n2"]["from"] == "n1" and by_to["n2"]["hop"] == 2
    assert by_to["n3"]["from"] == "n0" and by_to["n3"]["hop"] == 1
    assert tree["max_hop"] == 2
    # delivery times rebased back onto the fleet clock
    assert by_to["n1"]["t_s"] == pytest.approx(_T0 + 0.020, abs=1e-6)
    assert by_to["n3"]["t_s"] == pytest.approx(_T0 + 0.040, abs=1e-6)

    # full waterfall: every stage attributed, in spec order
    w = rec["waterfall"]
    assert w["span_s"] == pytest.approx(0.100, abs=1e-6)
    names = [s["stage"] for s in w["stages"]]
    assert names == [n for n, _ in fleettrace.WATERFALL]
    durs = {s["stage"]: s["dur_s"] for s in w["stages"]}
    assert durs["proposal_build"] == pytest.approx(0.010, abs=1e-6)
    assert durs["gossip_first_delivery"] == pytest.approx(0.010, abs=1e-6)
    assert durs["gossip_last_delivery"] == pytest.approx(0.020, abs=1e-6)
    assert durs["prevote_quorum"] == pytest.approx(0.020, abs=1e-6)
    assert durs["precommit_quorum"] == pytest.approx(0.020, abs=1e-6)
    assert durs["commit"] == pytest.approx(0.010, abs=1e-6)
    assert durs["apply"] == pytest.approx(0.010, abs=1e-6)
    assert w["coverage"] == pytest.approx(1.0, abs=1e-4)
    assert w["unaccounted_s"] == pytest.approx(0.0, abs=1e-5)

    # straggler ranking: validator 3's prevote landed last
    assert rec["stragglers"][0]["validator_index"] == 3
    assert rec["stragglers"][0]["latency_s"] == pytest.approx(
        0.045, abs=1e-5)
    assert rec["round_churn"] is False


def test_stitch_missing_marks_stay_unaccounted():
    """Drop both quorum marks: the commit boundary is no longer
    adjacent to the last present boundary, so the quorum→commit span is
    honest unaccounted time and coverage falls to 50% — the acceptance
    oracle fails on mark loss instead of silently passing."""
    nodes = _golden_nodes()
    del nodes[0]["timeline"]["marks"]["prevote_23"]
    del nodes[0]["timeline"]["marks"]["precommit_23"]
    rec = fleettrace.stitch_height(9, nodes)
    w = rec["waterfall"]
    assert [s["stage"] for s in w["stages"]] == [
        "proposal_build", "gossip_first_delivery",
        "gossip_last_delivery", "apply"]
    assert w["attributed_s"] == pytest.approx(0.050, abs=1e-5)
    assert w["unaccounted_s"] == pytest.approx(0.050, abs=1e-5)
    assert w["coverage"] == pytest.approx(0.5, abs=1e-3)


def test_stitch_degenerate_inputs():
    assert fleettrace.stitch_height(1, []) is None
    # no proposer anywhere (every proposal came from a peer, no emit)
    orphan = {"name": "x", "node_id": "idx", "offset_s": 0.0,
              "uncertainty_s": 0.0,
              "timeline": _tl(
                  {"proposal_received": _mark(1.0, 0.0, "ghost")})}
    assert fleettrace.stitch_height(1, [orphan]) is None


# --- commit-stage splice + exports ------------------------------------


def test_parse_commit_stages():
    body = (
        "# TYPE tendermint_commit_stage_seconds histogram\n"
        'tendermint_commit_stage_seconds_sum{stage="wal_fsync"} 0.5\n'
        'tendermint_commit_stage_seconds_count{stage="wal_fsync"} 10\n'
        'tendermint_commit_stage_seconds_sum{stage="apply"} 1.25\n'
        'tendermint_commit_stage_seconds_count{stage="apply"} 10\n'
        "unrelated_total 3\n")
    out = fleettrace.parse_commit_stages(body)
    assert out == {
        "wal_fsync": {"total_s": 0.5, "count": 10.0},
        "apply": {"total_s": 1.25, "count": 10.0},
    }
    assert fleettrace.parse_commit_stages("nothing 1\n") == {}


def test_chrome_trace_and_summarize():
    nodes = _golden_nodes()
    rec = fleettrace.stitch_height(9, nodes)
    doc = fleettrace.chrome_trace([rec], nodes)
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {"n0", "n1", "n2", "n3"}
    stages = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(stages) == len(fleettrace.WATERFALL)
    assert all(e["name"].startswith("h9:") for e in stages)
    deliveries = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(deliveries) == 3
    json.dumps(doc)  # JSON-serializable end to end

    text = fleettrace.summarize(rec)
    assert "height 9" in text and "proposer=n0" in text
    assert "deliver -> n2 via n1 hop=2" in text
    assert "slowest validators" in text and "v3+" in text


# --- collector with injected fetchers ---------------------------------


class _FakeFleet:
    """Two fake nodes behind injectable fetchers: n0 skewed +0.25s,
    n1 -0.25s, n0 proposes heights 5..7 but n1 only saw 6..7."""

    def __init__(self):
        self.skews = {"n0:1": 0.25, "n1:1": -0.25}
        self.ids = {"n0:1": "id0", "n1:1": "id1"}
        self.heights = {"n0:1": [5, 6, 7], "n1:1": [6, 7]}

    def _timeline(self, ep, h):
        base, skew = 100.0 * h, self.skews[ep]
        if ep == "n0:1":
            return _tl({
                "new_height": _mark(base, skew),
                "proposal_emit": _mark(base + 0.01, skew),
                "prevote_23": _mark(base + 0.05, skew),
                "precommit_23": _mark(base + 0.07, skew),
                "commit": _mark(base + 0.09, skew),
                "apply_block": _mark(base + 0.10, skew),
            })
        return _tl(
            {"proposal_received": _mark(base + 0.02, skew, "id0")})

    def fetch_json(self, url, timeout=5.0):
        _, _, rest = url.partition("http://")
        ep, _, path = rest.partition("/")
        if path == "debug/clock":
            return {"wall_s": time.time() + self.skews[ep],
                    "mono_ns": 0,
                    "identity": {"node_id": self.ids[ep]}}
        if path == "debug/timeline?list=1":
            return {"heights": self.heights[ep],
                    "latest": self.heights[ep][-1]}
        if path.startswith("debug/timeline?height="):
            h = int(path.rsplit("=", 1)[1])
            if h not in self.heights[ep]:
                raise KeyError(h)
            return self._timeline(ep, h)
        if path == "debug/exec":
            return {"enabled": True, "lanes": {}, "blocks": {}}
        raise AssertionError(f"unexpected url {url}")

    def fetch_text(self, url, timeout=5.0):
        assert url == "http://m0:1/metrics"
        return ('tendermint_commit_stage_seconds_sum'
                '{stage="wal_fsync"} 0.25\n'
                'tendermint_commit_stage_seconds_count'
                '{stage="wal_fsync"} 5\n')


def test_fleettrace_collector_stitches_common_heights(tmp_path):
    fake = _FakeFleet()
    hist = tmp_path / "fleet.jsonl"
    ft = fleettrace.FleetTrace(
        ["n0:1", "n1:1"], probes=3,
        fetch_json=fake.fetch_json, fetch_text=fake.fetch_text,
        scrape_metrics={"n0:1": "m0:1"}, history_path=str(hist))

    # offsets recovered against the collector clock: the fetchers are
    # in-process calls, so the probe error is microseconds
    probes = ft.probe_all()
    for ep, skew in fake.skews.items():
        assert probes[ep]["offset_s"] == pytest.approx(skew, abs=0.05)
        assert probes[ep]["identity"]["node_id"] == fake.ids[ep]

    # only heights EVERY node saw are stitchable
    assert ft.heights(last=4) == [6, 7]

    res = ft.collect()
    assert res["heights"] == [6, 7]
    assert [r["height"] for r in res["stitched"]] == [6, 7]
    for rec in res["stitched"]:
        assert rec["tree"]["proposer"] == "n0:1"
        assert rec["tree"]["edges"][0]["to"] == "n1:1"
        # the commit-stage splice rode in from the metrics endpoint
        assert rec["commit_stages"]["n0:1"]["wal_fsync"]["count"] == 5
    assert set(res["exec"]) == {"n0:1", "n1:1"}

    # JSONL history: one parseable stitched record per line
    lines = [json.loads(ln) for ln in
             hist.read_text().strip().splitlines()]
    assert [r["height"] for r in lines] == [6, 7]


# --- exec-lane flight recorder ----------------------------------------


def test_flight_recorder_rings_and_percentiles():
    from tendermint_tpu.state.parallel import FlightRecorder

    fr = FlightRecorder(samples=4)
    assert fr.enabled
    fr.record_lane(0, 1000, 9000, txs=5, groups=2)
    fr.record_lane(0, 3000, 7000, txs=5, groups=1)
    fr.record_lane(1, -50, 0, txs=0, groups=0)  # negatives clamp to 0
    fr.note_block(10, 8, conflicts=2, serial_fallback=False, lanes=2)
    fr.note_block(4, 0, conflicts=0, serial_fallback=True, lanes=2)

    rep = fr.report()
    assert set(rep) == {"enabled", "capacity", "lanes", "blocks"}
    lane0 = rep["lanes"]["0"]
    assert lane0["samples"] == 2
    assert lane0["txs"] == 10 and lane0["groups"] == 3
    # busy 16µs of a 20µs lifetime
    assert lane0["busy_ratio"] == pytest.approx(0.8)
    assert rep["lanes"]["1"]["busy_ratio"] == 0.0
    assert rep["blocks"]["count"] == 2
    assert rep["blocks"]["conflict_txs"] == 2
    assert rep["blocks"]["serial_fallbacks"] == 1
    assert rep["blocks"]["recent"][-1]["serial_fallback"] is True
    json.dumps(rep)  # /debug/exec payload must serialize

    wp = fr.wakeup_percentiles()
    assert wp["count"] == 3
    assert wp["p50_s"] == pytest.approx(1000 / 1e9)
    assert wp["p99_s"] == pytest.approx(3000 / 1e9)

    # shrink-in-place keeps only the newest samples
    fr.configure(samples=1)
    assert fr.report()["lanes"]["0"]["samples"] == 1
    fr.configure(enabled=False)
    assert fr.report()["enabled"] is False
    fr.reset()
    rep = fr.report()
    assert rep["lanes"] == {} and rep["blocks"]["count"] == 0


def test_flight_recorder_metrics_sink():
    from tendermint_tpu.state.parallel import FlightRecorder

    observed, gauges = [], {}

    class _Hist:
        def observe(self, v):
            observed.append(v)

    class _Gauge:
        def with_labels(self, lane):
            class _S:
                def set(_self, v):
                    gauges[lane] = v
            return _S()

    class _Sink:
        exec_lane_wakeup = _Hist()
        exec_lane_busy = _Gauge()

    fr = FlightRecorder(samples=8)
    fr.set_metrics(_Sink())
    fr.record_lane(2, 2_000_000, 8_000_000, txs=1, groups=1)
    assert observed == [pytest.approx(0.002)]
    assert gauges["2"] == pytest.approx(0.8)
    fr.set_metrics(None)
    fr.record_lane(2, 1_000_000, 1_000_000, txs=1, groups=1)
    assert len(observed) == 1  # sink uninstalled, nothing observed


# --- tier-1 provider contract -----------------------------------------

_DEBUG_ROUTES = ("consensus", "statesync", "abci", "mempool", "crypto",
                 "rpc", "lockdep", "recovery", "determinism", "exec",
                 "incidents", "handel", "replica")


def _scrape(addr, path):
    with urllib.request.urlopen(
            f"http://{addr}{path}", timeout=10) as r:
        return json.loads(r.read().decode())


def _assert_provider_contract(addr, node_id, mode):
    # every provider answers JSON and keeps its top-level schema stable
    # across scrapes (the fleet collector's compatibility contract)
    first = {rt: _scrape(addr, f"/debug/{rt}") for rt in _DEBUG_ROUTES}
    for rt, payload in first.items():
        assert isinstance(payload, dict), (mode, rt)
    second = {rt: _scrape(addr, f"/debug/{rt}") for rt in _DEBUG_ROUTES}
    for rt in _DEBUG_ROUTES:
        assert set(second[rt]) == set(first[rt]), (
            f"{mode}: /debug/{rt} schema drifted between scrapes: "
            f"{sorted(set(first[rt]) ^ set(second[rt]))}")

    inc = first["incidents"]
    assert set(inc) == {"entries", "open", "counts", "last_height",
                        "skew_s"}, (mode, sorted(inc))
    assert set(inc["counts"]) == {"injection", "heal", "detection",
                                  "recovery"}
    assert inc["open"] == []  # fault-free boot: nothing open

    ex = first["exec"]
    assert set(ex) == {"enabled", "capacity", "lanes", "blocks",
                       "parallel_lanes", "lane_pool", "retry"}, (
        mode, sorted(ex))
    assert set(ex["blocks"]) == {"count", "conflict_txs",
                                 "serial_fallbacks", "retry_rounds_p99",
                                 "dispatch_p50_us", "dispatch_p99_us",
                                 "recent"}
    assert set(ex["retry"]) == {"retry_rounds_p99", "retried_txs",
                                "steals", "steal_ratio"}

    rep = first["replica"]
    if mode == "replica":
        # the fan-out tree view: full payload, even with no peers yet
        assert set(rep) == {"enabled", "mode", "parent", "orphaned",
                            "depth", "chain", "lag_blocks", "switches",
                            "last_reason", "behind_horizon",
                            "prefer_replicas", "max_depth",
                            "lag_budget_blocks", "candidates"}, (
            mode, sorted(rep))
        assert rep["enabled"] is True and rep["mode"] == "replica"
        assert rep["orphaned"] is True and rep["parent"] == ""
        assert rep["candidates"] == []
    else:
        # full/validator nodes answer the route but stay disabled, so
        # fleet scrapers never special-case node modes
        assert rep["enabled"] is False
        assert "mode" in rep

    clk = _scrape(addr, "/debug/clock")
    assert set(clk) == {"wall_s", "mono_ns", "identity"}
    assert clk["identity"]["node_id"] == node_id
    assert abs(clk["wall_s"] - time.time()) < 5.0
    return first


def test_debug_provider_contract_validator_mode(tmp_path):
    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK,
        query_for_event,
    )

    c = make_config(tmp_path, "prov")
    c.base.prof_laddr = "tcp://127.0.0.1:0"
    init_files(c)
    node = default_new_node(c)
    sub = node.event_bus.subscribe(
        "prov", query_for_event(EVENT_NEW_BLOCK), 16)
    node.start()
    try:
        h, deadline = 0, time.time() + 30
        while h < 2 and time.time() < deadline:
            m = sub.get(timeout=1.0)
            if m is not None:
                h = m.data["block"].header.height
        assert h >= 2

        addr = node._prof_server.listen_addr
        payloads = _assert_provider_contract(
            addr, node.node_key.id, "validator")
        assert payloads["consensus"]["live"]["round_state"]["height"] >= 1
        # the ?list=1 satellite: heights inventory for the collector
        listing = _scrape(addr, "/debug/timeline?list=1")
        assert set(listing) == {"heights", "latest"}
        assert listing["latest"] >= 2
        assert listing["latest"] in listing["heights"]
    finally:
        node.stop()


def test_debug_provider_contract_replica_mode(tmp_path):
    """Replica boots (no consensus machinery, no peers, statesync off)
    must serve the same /debug/* surface — including /debug/exec — so
    fleet scrapers never special-case node modes."""
    from tendermint_tpu.node import default_new_node

    c = make_config(tmp_path, "replica")
    c.base.mode = "replica"
    c.base.prof_laddr = "tcp://127.0.0.1:0"
    c.statesync.enable = False
    init_files(c)
    node = default_new_node(c)
    node.start()
    try:
        assert node.consensus_state is None
        addr = node._prof_server.listen_addr
        payloads = _assert_provider_contract(
            addr, node.node_key.id, "replica")
        assert payloads["consensus"]["mode"] == "replica"
    finally:
        node.stop()


# --- monitor history sink ---------------------------------------------


def test_monitor_history_jsonl(tmp_path):
    from test_observability import _stub_debug_server

    from tendermint_tpu.tools.monitor import Monitor

    srv, daddr = _stub_debug_server({"height": 3, "stalls_total": 0})
    hist = tmp_path / "history.jsonl"
    mon = Monitor(["127.0.0.1:1"], poll_interval=0.2,
                  debug_addrs=[daddr], history_path=str(hist))
    mon.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if hist.exists() and hist.read_text().count("\n") >= 2:
                break
            time.sleep(0.05)
    finally:
        mon.stop()
        srv.shutdown()
    lines = [json.loads(ln) for ln in
             hist.read_text().strip().splitlines()]
    assert len(lines) >= 2
    for entry in lines:
        assert entry["t"] > 0
        assert "snapshot" in entry


# --- slow: the live acceptance oracle ---------------------------------


@pytest.mark.slow
def test_proptrace_scenario_end_to_end():
    """The PR's acceptance gate over real HTTP: a 4-node localnet with
    ±0.5s injected clock skews; fleettrace must recover every offset to
    ≤10ms on loopback and attribute ≥95% of each stitched block's
    proposal→apply span to named stages."""
    from tendermint_tpu.tools import scenarios

    res = scenarios.run("proptrace", seed=8, n=4)
    assert res["converged"] and res["safety_ok"], res
    assert res["offsets_ok"], res["offset_error_ms"]
    assert res["coverage_ok"], (res["coverages"],
                                res["stitched_heights"])
    assert res["coverage_min"] >= 0.95
    assert res["max_hop"] >= 1
    assert res["ok"], res
