"""SecretConnection + MConnection tests (reference p2p/conn/*_test.go)."""

import socket
import threading
import time

import pytest

from tendermint_tpu.crypto.keys import PrivKeyEd25519
from tendermint_tpu.libs.flowrate import Monitor
from tendermint_tpu.p2p.base_reactor import ChannelDescriptor
from tendermint_tpu.p2p.conn.connection import MConnConfig, MConnection
from tendermint_tpu.p2p.conn.secret_connection import AuthError, SecretConnection
from tendermint_tpu.p2p.key import node_id


def _socket_pair():
    a, b = socket.socketpair()
    return a, b


def _make_secret_pair(k1=None, k2=None):
    k1 = k1 or PrivKeyEd25519.generate()
    k2 = k2 or PrivKeyEd25519.generate()
    s1, s2 = _socket_pair()
    out = {}

    def server():
        out["sc2"] = SecretConnection(s2, k2)

    t = threading.Thread(target=server)
    t.start()
    sc1 = SecretConnection(s1, k1)
    t.join(timeout=5)
    return sc1, out["sc2"], k1, k2


class TestSecretConnection:
    def test_handshake_authenticates_remote_key(self):
        sc1, sc2, k1, k2 = _make_secret_pair()
        assert sc1.remote_pub_key().bytes() == k2.pub_key().bytes()
        assert sc2.remote_pub_key().bytes() == k1.pub_key().bytes()

    def test_roundtrip_small(self):
        sc1, sc2, _, _ = _make_secret_pair()
        sc1.write(b"hello world")
        assert sc2.read_exact(11) == b"hello world"
        sc2.write(b"pong")
        assert sc1.read_exact(4) == b"pong"

    def test_roundtrip_multi_frame(self):
        sc1, sc2, _, _ = _make_secret_pair()
        blob = bytes(range(256)) * 40  # 10240B > 1024-frame payload
        done = {}

        def rx():
            done["got"] = sc2.read_exact(len(blob))

        t = threading.Thread(target=rx)
        t.start()
        sc1.write(blob)
        t.join(timeout=5)
        assert done["got"] == blob

    def test_ciphertext_differs_from_plaintext(self):
        """The raw socket must never carry plaintext."""
        a, b = _socket_pair()
        k1, k2 = PrivKeyEd25519.generate(), PrivKeyEd25519.generate()
        captured = []

        class Tap:
            def __init__(self, s):
                self.s = s

            def sendall(self, data):
                captured.append(bytes(data))
                self.s.sendall(data)

            def recv(self, n):
                return self.s.recv(n)

            def settimeout(self, t):
                self.s.settimeout(t)

            def close(self):
                self.s.close()

            def shutdown(self, how):
                self.s.shutdown(how)

        out = {}
        t = threading.Thread(target=lambda: out.update(sc=SecretConnection(b, k2)))
        t.start()
        sc1 = SecretConnection(Tap(a), k1)
        t.join(timeout=5)
        secret = b"SUPER-SECRET-PLAINTEXT"
        sc1.write(secret)
        out["sc"].read_exact(len(secret))
        assert all(secret not in c for c in captured)

    def test_tampered_frame_fails(self):
        a, b = _socket_pair()
        k1, k2 = PrivKeyEd25519.generate(), PrivKeyEd25519.generate()

        out, errs = {}, []

        def server():
            try:
                sc = SecretConnection(b, k2)
                out["sc"] = sc
                sc.read_exact(5)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=server)
        t.start()
        sc1 = SecretConnection(a, k1)
        # flip a bit in the next sealed frame by writing garbage directly
        a.sendall(b"\x00" * (1028 + 16))
        t.join(timeout=5)
        assert errs, "tampered frame must not decrypt"


def _mconn_pair(descs, cfg=None):
    sc1, sc2, _, _ = _make_secret_pair()
    rx1, rx2 = [], []
    ev1, ev2 = threading.Event(), threading.Event()
    m1 = MConnection(
        sc1, descs, lambda ch, b: (rx1.append((ch, b)), ev1.set()), lambda e: None, cfg
    )
    m2 = MConnection(
        sc2, descs, lambda ch, b: (rx2.append((ch, b)), ev2.set()), lambda e: None, cfg
    )
    m1.start()
    m2.start()
    return m1, m2, rx1, rx2, ev1, ev2


class TestMConnection:
    def test_send_receive(self):
        descs = [ChannelDescriptor(id=0x20, priority=5), ChannelDescriptor(id=0x30, priority=1)]
        m1, m2, rx1, rx2, ev1, ev2 = _mconn_pair(descs)
        try:
            assert m1.send(0x20, b"vote-data")
            assert ev2.wait(5)
            assert rx2 == [(0x20, b"vote-data")]
            ev2.clear()
            assert m2.send(0x30, b"tx-data")
            assert ev1.wait(5)
            assert rx1 == [(0x30, b"tx-data")]
        finally:
            m1.stop()
            m2.stop()

    def test_large_message_packetized(self):
        descs = [ChannelDescriptor(id=0x40, priority=1)]
        m1, m2, _, rx2, _, ev2 = _mconn_pair(descs)
        try:
            blob = b"\xab" * 5000  # > 4 packets
            assert m1.send(0x40, blob)
            assert ev2.wait(5)
            assert rx2 == [(0x40, blob)]
        finally:
            m1.stop()
            m2.stop()

    def test_unknown_channel_rejected(self):
        descs = [ChannelDescriptor(id=0x20, priority=1)]
        m1, m2, *_ = _mconn_pair(descs)
        try:
            assert not m1.send(0x99, b"x")
        finally:
            m1.stop()
            m2.stop()

    def test_ping_pong(self):
        descs = [ChannelDescriptor(id=0x20, priority=1)]
        cfg = MConnConfig(ping_interval=0.1, pong_timeout=2.0)
        m1, m2, *_ = _mconn_pair(descs, cfg)
        try:
            t0 = m1._last_pong
            time.sleep(0.5)
            assert m1._last_pong > t0, "pongs should have arrived"
        finally:
            m1.stop()
            m2.stop()


class TestFlowrate:
    def test_monitor_tracks_total(self):
        m = Monitor()
        m.update(1000)
        m.update(500)
        assert m.total == 1500

    def test_limit_throttles(self):
        m = Monitor()
        t0 = time.monotonic()
        moved = 0
        while moved < 3000:
            n = m.limit(1000, 10000)  # 10KB/s
            m.update(n)
            moved += n
        assert time.monotonic() - t0 > 0.2  # 3KB at 10KB/s ≳ 0.3s


class TestNodeID:
    def test_id_is_pubkey_address_hex(self):
        k = PrivKeyEd25519.generate()
        assert node_id(k.pub_key()) == k.pub_key().address().hex()
        assert len(node_id(k.pub_key())) == 40
